"""Host-sync discipline pass: hot paths must not grow implicit host syncs.

Within hot-path modules (lint.HOT_MODULES, or any file carrying a
`# ktpu: hot-path` pragma), flags:

- `.item()` calls and `.block_until_ready()` / `jax.block_until_ready`;
- `.copy_to_host_async()` — async initiation, but still a d2h transfer
  that belongs in the greppable budget (and may trip the transfer guard
  on real accelerators — every site carries an allow_transfer scope);
- `jax.device_get`, `to_host` (the multihost device-get wrapper),
  `np.asarray` / `np.array` — host materialization of device values;
- `int()` / `float()` / `bool()` applied to array-valued expressions
  (blocking device-to-host readback through `__int__`/`__bool__`);
- Python `if`/`while` branching on traced/array values (an implicit
  `bool()` sync).

"Array-valued" is a function-local taint analysis: `jnp.*` / `jax.lax.*`
expressions and calls to known jitted entries (the package-wide jit table,
plus local aliases like `fn = run_windows_donated if ... else run_windows`)
are sources; taint propagates through names assigned from tainted
expressions, through `self.X` attributes assigned from tainted expressions
anywhere in the same class, and through arithmetic/subscripts/attribute
access — but NOT through the sync calls themselves (`int(...)`,
`to_host(...)`, `np.asarray(...)` yield host values: the sync is flagged
at the conversion, and downstream host logic stays clean). `is`/`is not`
comparisons, `hasattr`, `isinstance`, `len` and `.shape`/`.dtype`/`.ndim`
reads never sync and never taint.

Every legitimate sync carries `# ktpu: sync-ok(<reason>)` on its line — or
on the enclosing `def` line to waive a whole (cold-path) function — which
makes the hot paths' sync budget greppable:
    grep -rn "ktpu: sync-ok" kubernetriks_tpu/
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from kubernetriks_tpu.lint import (
    LintContext,
    SourceFile,
    Violation,
    dotted_name,
    is_hot,
    local_entry_aliases,
)

PASS_ID = "hostsync"

_SYNC_FUNCS = {
    "jax.device_get": "jax.device_get",
    "device_get": "device_get",
    "jax.block_until_ready": "jax.block_until_ready",
    "block_until_ready": "block_until_ready",
    "to_host": "to_host (device-to-host fetch)",
    "np.asarray": "np.asarray on device values",
    "np.array": "np.array on device values",
    "numpy.asarray": "np.asarray on device values",
    "numpy.array": "np.array on device values",
}
_SYNC_METHODS = {"item", "block_until_ready", "copy_to_host_async"}
_CAST_FUNCS = {"int", "float", "bool"}
# Never sync and never propagate taint.
_NEUTRAL_FUNCS = {"hasattr", "isinstance", "len", "getattr", "type", "id"}
_NEUTRAL_ATTRS = {"shape", "dtype", "ndim", "size", "sharding"}
_TAINT_ROOTS = ("jnp.", "jax.")


class _ClassTaint:
    """self.X attributes assigned from tainted expressions anywhere in a
    class body taint `self.X` reads in every method of that class."""

    def __init__(self):
        self.attrs: Set[str] = set()


class _FunctionChecker:
    def __init__(
        self,
        sf: SourceFile,
        ctx: LintContext,
        fn: ast.FunctionDef,
        class_taint: Optional[_ClassTaint],
        violations: List[Violation],
    ):
        self.sf = sf
        self.ctx = ctx
        self.fn = fn
        self.class_taint = class_taint
        self.violations = violations
        self.tainted: Set[str] = set()
        # Non-recording probe: the def-scoped waiver only counts as USED
        # (stale-waiver accounting) when it actually suppresses a flag.
        self.fn_waived = sf.has_waiver(fn.lineno, PASS_ID)
        self.jit_like = self._local_jit_aliases()

    def _local_jit_aliases(self) -> Set[str]:
        return set(self.ctx.jit_names) | set(
            local_entry_aliases(self.fn, self.ctx.jit_names)
        )

    # -- taint ----------------------------------------------------------------

    def _is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname is not None:
                bare = fname.rsplit(".", 1)[-1]
                if fname in _SYNC_FUNCS or bare in _CAST_FUNCS:
                    return False  # conversion yields a host value
                if bare in _NEUTRAL_FUNCS:
                    return False
                if fname.startswith(_TAINT_ROOTS) or bare in self.jit_like:
                    return True
            # method calls on tainted receivers stay tainted (.sum(), .any())
            if isinstance(node.func, ast.Attribute):
                if node.func.attr in _SYNC_METHODS:
                    return False
                return self._is_tainted(node.func.value)
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in _NEUTRAL_ATTRS:
                return False
            path = dotted_name(node)
            if path is not None:
                if path in self.tainted:
                    return True
                if (
                    self.class_taint is not None
                    and path.startswith("self.")
                    and path.split(".")[1] in self.class_taint.attrs
                ):
                    return True
                if path.startswith(_TAINT_ROOTS):
                    return False  # module constant like jnp.int32
            return self._is_tainted(node.value)
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Subscript):
            return self._is_tainted(node.value)
        if isinstance(node, ast.BinOp):
            return self._is_tainted(node.left) or self._is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._is_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self._is_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not y` never reads the array's value.
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return self._is_tainted(node.left) or any(
                self._is_tainted(c) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return self._is_tainted(node.body) or self._is_tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self._is_tainted(node.value)
        return False

    def _assign_taint(self, targets, value) -> None:
        tainted = self._is_tainted(value)

        def mark(tgt, is_tainted):
            if isinstance(tgt, (ast.Tuple, ast.List)):
                # tuple unpack of a tainted rhs taints every element
                for e in tgt.elts:
                    mark(e, is_tainted)
                return
            path = dotted_name(tgt)
            if path is None:
                return
            if is_tainted:
                self.tainted.add(path)
            else:
                self.tainted.discard(path)

        for tgt in targets:
            mark(tgt, tainted)

    # -- violations -----------------------------------------------------------

    def _flag(self, node: ast.AST, message: str) -> None:
        line = node.lineno
        if self.sf.waived(line, PASS_ID):
            return
        if self.fn_waived:
            self.sf.waived(self.fn.lineno, PASS_ID)  # record def-waiver use
            return
        self.violations.append(
            Violation(
                self.sf.path,
                line,
                PASS_ID,
                f"{message} in hot-path module; waive a legitimate sync "
                "with # ktpu: sync-ok(reason)",
            )
        )

    def _check_expr(self, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fname = dotted_name(sub.func)
            if fname in _SYNC_FUNCS:
                self._flag(sub, f"host sync: {_SYNC_FUNCS[fname]}")
                continue
            if (
                isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _SYNC_METHODS
                and not sub.args
            ):
                self._flag(sub, f"host sync: .{sub.func.attr}()")
                continue
            if (
                fname in _CAST_FUNCS
                and len(sub.args) == 1
                and self._is_tainted(sub.args[0])
            ):
                self._flag(
                    sub,
                    f"host sync: {fname}() on an array-valued expression "
                    "(blocking device-to-host readback)",
                )

    # -- walk -----------------------------------------------------------------

    def run(self) -> None:
        self.visit_stmts(self.fn.body)

    def visit_stmts(self, stmts) -> None:
        for st in stmts:
            self.visit_stmt(st)

    def visit_stmt(self, st: ast.stmt) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(st, (ast.If, ast.While)):
            self._check_expr(st.test)
            if self._is_tainted(st.test):
                self._flag(
                    st,
                    "Python branch on a traced/array value (implicit bool() "
                    "sync)",
                )
            for body in (st.body, st.orelse):
                self.visit_stmts(body)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._check_expr(st.iter)
            if self._is_tainted(st.iter):
                self._flag(st, "Python iteration over a traced/array value")
            self.visit_stmts(st.body)
            self.visit_stmts(st.orelse)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._check_expr(item.context_expr)
            self.visit_stmts(st.body)
            return
        if isinstance(st, ast.Try):
            self.visit_stmts(st.body)
            for handler in st.handlers:
                self.visit_stmts(handler.body)
            self.visit_stmts(st.orelse)
            self.visit_stmts(st.finalbody)
            return
        # simple statement: check expressions, then propagate assignment taint
        for fld, value in ast.iter_fields(st):
            if isinstance(value, ast.expr):
                self._check_expr(value)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.expr):
                        self._check_expr(v)
        if isinstance(st, ast.Assign):
            self._assign_taint(st.targets, st.value)
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            self._assign_taint([st.target], st.value)
        elif isinstance(st, ast.AugAssign):
            if self._is_tainted(st.value):
                path = dotted_name(st.target)
                if path is not None:
                    self.tainted.add(path)


def _collect_class_taint(cls: ast.ClassDef, ctx: LintContext) -> _ClassTaint:
    taint = _ClassTaint()

    def expr_seeds(node, jit_names) -> bool:
        """Seed-level taint for class attrs: jnp/jax expressions and jitted
        calls (no fixpoint across methods — one level is what the real
        code needs: self.state / self._pending_shift style mirrors)."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                fname = dotted_name(sub.func)
                if fname is not None:
                    bare = fname.rsplit(".", 1)[-1]
                    if bare in ("int", "float", "bool", "to_host", "asarray"):
                        return False
                    if fname.startswith(_TAINT_ROOTS) or bare in jit_names:
                        return True
        return False

    # Collect names assigned from jitted-call results per method, then mark
    # self.X = <such name> too (the `state, rank, p = fn(...)` ->
    # `self.state = state` pattern).
    for method in cls.body:
        if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        jit_like = set(ctx.jit_names) | set(
            local_entry_aliases(method, ctx.jit_names)
        )
        local_tainted: Set[str] = set()
        for node in ast.walk(method):
            if not isinstance(node, ast.Assign):
                continue
            value_tainted = expr_seeds(node.value, jit_like)
            if not value_tainted:
                name = dotted_name(node.value)
                value_tainted = name in local_tainted if name else False
            for tgt in node.targets:
                elts = (
                    tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) else [tgt]
                )
                for e in elts:
                    path = dotted_name(e)
                    if path is None:
                        continue
                    if value_tainted:
                        if path.startswith("self."):
                            taint.attrs.add(path.split(".")[1])
                        else:
                            local_tainted.add(path)
    return taint


def check(ctx: LintContext) -> List[Violation]:
    violations: List[Violation] = []
    for sf in ctx.files:
        if not is_hot(sf):
            continue
        # top-level functions
        for node in sf.tree.body if isinstance(sf.tree, ast.Module) else []:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _FunctionChecker(sf, ctx, node, None, violations).run()
            elif isinstance(node, ast.ClassDef):
                taint = _collect_class_taint(node, ctx)
                for method in node.body:
                    if isinstance(
                        method, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        _FunctionChecker(
                            sf, ctx, method, taint, violations
                        ).run()
    return violations
