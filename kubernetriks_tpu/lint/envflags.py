"""Env-flag registry pass: KTPU_*/KUBERNETRIKS_* reads go through flags.py.

Before PR 6, `"0"` / empty / unset truthiness was decided ad hoc at each
read site — three different parsing rules across engine.py/step.py/tests,
one of which made `KUBERNETRIKS_FAST_TESTS=0` truthy. The central registry
(`kubernetriks_tpu/flags.py`: name, type, default, doc, one truthiness
parser) is the single owner; this pass enforces it:

- any `os.environ.get` / `os.getenv` / `os.environ[...]` /
  `... in os.environ` READ of a literal KTPU_* or KUBERNETRIKS_* name
  outside flags.py is a violation — call `flags.flag_bool` /
  `flag_tristate` / `flag_str` / `flag_int` instead;
- a read (anywhere, flags.py included) of a name not in the registry is a
  violation — declare it first.

Writes (`os.environ[K] = v`, monkeypatch.setenv) are not reads and pass.
Waive with `# ktpu: flag-ok(<reason>)`.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from kubernetriks_tpu.lint import LintContext, SourceFile, Violation, dotted_name

PASS_ID = "envflags"

_NAME_RE = re.compile(r"^(KTPU|KUBERNETRIKS)_[A-Z0-9_]+$")
_FLAGS_MODULE = "kubernetriks_tpu/flags.py"


def _registry():
    from kubernetriks_tpu.flags import REGISTRY

    return REGISTRY


def _literal_key(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _env_read_key(node: ast.AST) -> Optional[str]:
    """The literal key of an os.environ/os.getenv READ, else None."""
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname in ("os.environ.get", "os.getenv", "environ.get", "getenv"):
            if node.args:
                return _literal_key(node.args[0])
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        if dotted_name(node.value) in ("os.environ", "environ"):
            return _literal_key(node.slice)
    if isinstance(node, ast.Compare) and len(node.ops) == 1:
        if isinstance(node.ops[0], (ast.In, ast.NotIn)):
            if dotted_name(node.comparators[0]) in ("os.environ", "environ"):
                return _literal_key(node.left)
    return None


def check(ctx: LintContext) -> List[Violation]:
    registry = _registry()
    violations: List[Violation] = []
    for sf in ctx.files:
        in_flags = sf.path == _FLAGS_MODULE
        for node in ast.walk(sf.tree):
            key = _env_read_key(node)
            if key is None or not _NAME_RE.match(key):
                continue
            if sf.waived(node.lineno, PASS_ID):
                continue
            if not in_flags:
                violations.append(
                    Violation(
                        sf.path,
                        node.lineno,
                        PASS_ID,
                        f"direct environment read of {key!r}: go through "
                        "kubernetriks_tpu.flags (flag_bool / flag_tristate "
                        "/ flag_str / flag_int) so the name, type, default "
                        "and truthiness rule live in the registry",
                    )
                )
            if key not in registry:
                violations.append(
                    Violation(
                        sf.path,
                        node.lineno,
                        PASS_ID,
                        f"environment flag {key!r} is not declared in the "
                        "kubernetriks_tpu.flags registry (name, type, "
                        "default, doc)",
                    )
                )
    return violations
