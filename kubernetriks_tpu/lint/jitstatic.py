"""Jit-static discipline pass.

Three rules over the package-wide jit table (lint.build_context — every
`jax.jit` / `partial(jax.jit, ...)` site, decorator or assignment form,
with `static_argnames` resolved through module-level tuple constants and
`+` concatenations):

1. Every `static_argnames` entry must name a parameter of the wrapped
   function. A stale static name silently traces the (vanished or renamed)
   kwarg — the PR 2 `fault_params` regression class — or raises only at
   first call.
2. Paired donated/undonated entries (`X` and `X_donated` in the same
   module) must declare identical static sets: drift makes a kwarg static
   in one variant and traced in the other, so the "bit-identical" pair
   quietly compiles different programs (they drifted once already in
   step.py).
3. COUPLED window-program statics must travel together: an entry whose
   static set names one of a coupled pair (today: `fault_params` and
   `profile`, the two _STEP_STATICS config objects every window-program
   entry threads) but not the other has forked off the shared static
   set — the entry would compile the default scheduler pipeline (or the
   fault-free build) no matter what the engine configured, which is
   exactly the silent-wrong-profile failure mode the compiled-profile
   subsystem exists to kill.

Unresolvable `static_argnames` expressions (anything beyond literals,
module constants and `+`) are themselves violations: the discipline is
only checkable when the set is statically known.

Waive with `# ktpu: static-ok(<reason>)` on the jit site's line.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Tuple

from kubernetriks_tpu.lint import JitEntry, LintContext, Violation

PASS_ID = "jitstatic"

# Rule 3: statics that must co-occur in any entry naming one of them —
# the window-program config objects threaded through _STEP_STATICS.
COUPLED_STATICS: Tuple[Tuple[str, ...], ...] = (("fault_params", "profile"),)


def check(ctx: LintContext) -> List[Violation]:
    violations: List[Violation] = []
    by_file = {sf.path: sf for sf in ctx.files}

    def flag(entry: JitEntry, message: str) -> None:
        sf = by_file.get(entry.path)
        if sf is not None and sf.waived(entry.line, PASS_ID):
            return
        violations.append(Violation(entry.path, entry.line, PASS_ID, message))

    # Rule 1: statics name real parameters.
    for entry in ctx.jit_entries:
        if not entry.static_resolved:
            flag(
                entry,
                f"static_argnames of {entry.name} could not be resolved "
                "statically (use a literal tuple, a module-level tuple "
                "constant, or + concatenations of those)",
            )
            continue
        if entry.params is None:
            continue  # wrapped function defined elsewhere; nothing to check
        for static in entry.static_argnames or ():
            if static not in entry.params and not entry.has_varkw:
                flag(
                    entry,
                    f"static_argnames entry {static!r} of {entry.name} names "
                    "no parameter of the wrapped function (params: "
                    f"{', '.join(entry.params)})",
                )

    # Rule 3: coupled statics travel together. Only entries whose wrapped
    # function actually HAS both parameters are held to it — a kernel
    # wrapper with a profile static but no fault_params parameter is not a
    # window program and correctly declares only what it takes.
    for entry in ctx.jit_entries:
        if not entry.static_resolved:
            continue  # already flagged by rule 1
        statics = frozenset(entry.static_argnames or ())
        for pair in COUPLED_STATICS:
            present = [name for name in pair if name in statics]
            if not present or len(present) == len(pair):
                continue
            missing = [name for name in pair if name not in statics]
            if entry.params is not None and not entry.has_varkw and any(
                name not in entry.params for name in missing
            ):
                continue
            flag(
                entry,
                f"static_argnames of {entry.name} declares "
                f"{sorted(present)} but not {sorted(missing)} — the "
                "coupled window-program statics "
                f"{sorted(pair)} must travel together (thread them "
                "through the shared _STEP_STATICS tuple), or the entry "
                "silently compiles the default configuration for the "
                "missing one",
            )

    # Rule 2: donated/undonated pairs declare identical static sets.
    by_name: Dict[Tuple[str, str], List[JitEntry]] = defaultdict(list)
    for entry in ctx.jit_entries:
        by_name[(entry.path, entry.name)].append(entry)
    for (path, name), entries in sorted(by_name.items()):
        if not name.endswith("_donated"):
            continue
        base = by_name.get((path, name[: -len("_donated")]))
        if not base:
            continue
        donated_entry, base_entry = entries[0], base[0]
        if not (donated_entry.static_resolved and base_entry.static_resolved):
            continue  # already flagged by rule 1
        d_set = frozenset(donated_entry.static_argnames or ())
        b_set = frozenset(base_entry.static_argnames or ())
        if d_set != b_set:
            diff = sorted(d_set.symmetric_difference(b_set))
            flag(
                donated_entry,
                f"static sets of {name} and {base_entry.name} differ "
                f"(line {base_entry.line}): {diff} — paired "
                "donated/undonated entries must declare identical "
                "static_argnames or they compile different programs",
            )
    return violations
