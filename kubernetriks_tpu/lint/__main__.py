"""CLI: `python -m kubernetriks_tpu.lint [paths...]`.

Default scope is the repo's lintable surface: the package, bench.py,
tests/, scripts/ and experiments/ (the self-test fixtures under
tests/lint_fixtures/ are excluded — they hold seeded violations on
purpose; pass their paths explicitly to lint them, as tests/test_lint.py
does). Exit status: 0 clean, 1 violations (or stale waivers under
--strict-waivers), 2 usage error.

Machine-readable output: `--json PATH` writes {root, violations,
stale_waivers, counts} (PATH `-` for stdout); `--github` emits GitHub
Actions `::error` / `::warning` workflow annotations next to the plain
rendering (the CI lint job sets both and uploads the JSON artifact).
Stale waivers — a `# ktpu: *-ok(reason)` whose line/def no longer
triggers its pass — print as warnings by default; `--strict-waivers`
makes them exit-1 errors (detection needs every pass's usage record, so
it only runs when no --pass filter is given).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from kubernetriks_tpu.lint import (
    PASS_IDS,
    list_waivers,
    run_lint_report,
)

DEFAULT_SCOPE = (
    "kubernetriks_tpu",
    "bench.py",
    "tests",
    "scripts",
    "experiments",
)


def _find_root(start: str) -> str:
    """Repo root = nearest ancestor holding the package directory."""
    cur = os.path.abspath(start)
    while True:
        if os.path.isdir(os.path.join(cur, "kubernetriks_tpu")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start)
        cur = parent


def _github_annotation(kind: str, path: str, line: int, title: str, msg: str):
    # Workflow-command escaping per the Actions contract: message data
    # escapes %/CR/LF; PROPERTY values additionally escape ',' and ':'
    # (an unescaped comma in a path would truncate the annotation).
    def data(s: str) -> str:
        return s.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")

    def prop(s: str) -> str:
        return data(s).replace(",", "%2C").replace(":", "%3A")

    print(
        f"::{kind} file={prop(path)},line={line},title={prop(title)}"
        f"::{data(msg)}"
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetriks_tpu.lint",
        description="ktpu-lint: framework-invariant static analysis "
        "(donation safety, host-sync discipline, jit-static discipline, "
        "PRNG hygiene, env-flag registry, state-leaf coverage, "
        "scenario-trace discipline, shape contracts, feeder-lock "
        "discipline).",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/directories to lint (default: the repo surface)",
    )
    parser.add_argument(
        "--pass",
        dest="passes",
        action="append",
        choices=PASS_IDS,
        help="run only the named pass (repeatable; default: all nine)",
    )
    parser.add_argument(
        "--root",
        default=None,
        help="repo root for relative paths (default: auto-detected)",
    )
    parser.add_argument(
        "--list-waivers",
        action="store_true",
        help="print every # ktpu: *-ok(reason) waiver in scope (the "
        "greppable sync budget) and exit 0",
    )
    parser.add_argument(
        "--json",
        metavar="PATH",
        default=None,
        help="write machine-readable findings (violations + stale "
        "waivers) as JSON to PATH ('-' for stdout)",
    )
    parser.add_argument(
        "--github",
        action="store_true",
        help="emit GitHub Actions ::error/::warning annotations",
    )
    parser.add_argument(
        "--strict-waivers",
        action="store_true",
        help="treat stale waivers (a *-ok whose line no longer triggers "
        "its pass) as errors instead of warnings",
    )
    args = parser.parse_args(argv)

    root = os.path.abspath(args.root) if args.root else _find_root(os.getcwd())
    paths = args.paths or [
        p for p in DEFAULT_SCOPE if os.path.exists(os.path.join(root, p))
    ]
    if not paths:
        print("ktpu-lint: nothing to lint", file=sys.stderr)
        return 2

    if args.list_waivers:
        for line in list_waivers(paths, root):
            print(line)
        return 0

    report = run_lint_report(paths, root, passes=args.passes)
    violations = report.violations
    # Stale detection is only sound when every pass recorded its waiver
    # usage over the scope — a --pass filter leaves the others' waivers
    # unjudged.
    stale = report.stale_waivers if not args.passes else []

    for v in violations:
        print(v.render())
        if args.github:
            _github_annotation(
                "error", v.path, v.line, f"ktpu-lint[{v.pass_id}]", v.message
            )
    for w in stale:
        print(w.render())
        if args.github:
            _github_annotation(
                "error" if args.strict_waivers else "warning",
                w.path,
                w.line,
                "ktpu-lint[stale-waiver]",
                w.message,
            )

    if args.json is not None:
        payload = {
            "root": root,
            "passes": list(args.passes or PASS_IDS),
            "violations": [v.as_json() for v in violations],
            "stale_waivers": [w.as_json() for w in stale],
            "counts": {
                "violations": len(violations),
                "stale_waivers": len(stale),
                "files": len({v.path for v in violations}),
            },
        }
        text = json.dumps(payload, indent=2) + "\n"
        if args.json == "-":
            sys.stdout.write(text)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(text)

    n_files = len({v.path for v in violations})
    failing = len(violations) + (len(stale) if args.strict_waivers else 0)
    if failing:
        parts = [f"{len(violations)} violation(s) in {n_files} file(s)"]
        if stale:
            parts.append(
                f"{len(stale)} stale waiver(s)"
                + ("" if args.strict_waivers else " [warnings]")
            )
        print("ktpu-lint: " + ", ".join(parts), file=sys.stderr)
        return 1
    if stale:
        print(
            f"ktpu-lint: clean, but {len(stale)} stale waiver(s) — run "
            "with --strict-waivers to fail on them",
            file=sys.stderr,
        )
        return 0
    print("ktpu-lint: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `| head` closed the pipe; not an error
        sys.exit(0)
