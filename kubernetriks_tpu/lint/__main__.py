"""CLI: `python -m kubernetriks_tpu.lint [paths...]`.

Default scope is the repo's lintable surface: the package, bench.py,
tests/, scripts/ and experiments/ (the self-test fixtures under
tests/lint_fixtures/ are excluded — they hold seeded violations on
purpose; pass their paths explicitly to lint them, as tests/test_lint.py
does). Exit status: 0 clean, 1 violations, 2 usage error.
"""

from __future__ import annotations

import argparse
import os
import sys

from kubernetriks_tpu.lint import PASS_IDS, list_waivers, run_lint

DEFAULT_SCOPE = (
    "kubernetriks_tpu",
    "bench.py",
    "tests",
    "scripts",
    "experiments",
)


def _find_root(start: str) -> str:
    """Repo root = nearest ancestor holding the package directory."""
    cur = os.path.abspath(start)
    while True:
        if os.path.isdir(os.path.join(cur, "kubernetriks_tpu")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start)
        cur = parent


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetriks_tpu.lint",
        description="ktpu-lint: framework-invariant static analysis "
        "(donation safety, host-sync discipline, jit-static discipline, "
        "PRNG hygiene, env-flag registry).",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/directories to lint (default: the repo surface)",
    )
    parser.add_argument(
        "--pass",
        dest="passes",
        action="append",
        choices=PASS_IDS,
        help="run only the named pass (repeatable; default: all five)",
    )
    parser.add_argument(
        "--root",
        default=None,
        help="repo root for relative paths (default: auto-detected)",
    )
    parser.add_argument(
        "--list-waivers",
        action="store_true",
        help="print every # ktpu: *-ok(reason) waiver in scope (the "
        "greppable sync budget) and exit 0",
    )
    args = parser.parse_args(argv)

    root = os.path.abspath(args.root) if args.root else _find_root(os.getcwd())
    paths = args.paths or [
        p for p in DEFAULT_SCOPE if os.path.exists(os.path.join(root, p))
    ]
    if not paths:
        print("ktpu-lint: nothing to lint", file=sys.stderr)
        return 2

    if args.list_waivers:
        for line in list_waivers(paths, root):
            print(line)
        return 0

    violations = run_lint(paths, root, passes=args.passes)
    for v in violations:
        print(v.render())
    n_files = len(
        {v.path for v in violations}
    )
    if violations:
        print(
            f"ktpu-lint: {len(violations)} violation(s) in {n_files} file(s)",
            file=sys.stderr,
        )
        return 1
    print("ktpu-lint: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `| head` closed the pipe; not an error
        sys.exit(0)
