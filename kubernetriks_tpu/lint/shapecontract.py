"""Shape-contract pass: per-cluster lanes broadcast on declared axes only.

The PR 13 bug class: per-lane `(C,)` control-law leaves (`hpa_tolerance`,
`ca_threshold`, ...) meet `(C, G)` / `(C, P)` per-object expressions in
the autoscaler math. NumPy broadcasting aligns from the RIGHT, so a bare
`util > st.hpa_tolerance` either explodes the shape or — when the axis
sizes happen to agree — silently broadcasts the lane vector across the
WRONG axis. The fixes are mechanical (`[:, None]`, `.T`,
`jnp.broadcast_to`); forgetting one is invisible until a heterogeneous
fleet diverges. This pass proves the mixes explicit.

Leaves carry declared axis signatures in `AXIS_SIGNATURES` registries
next to their NamedTuples (batched/state.py for state leaves,
batched/autoscale.py for autoscaler leaves; every in-scope registry is
merged). Signature grammar: comma-separated axis tokens, e.g. "C",
"C,G", "C,P", "C,*" (second axis intentionally unspecified — rank-only
checking), and "@node" for the lane-major-aware hot node leaves
(`state.NODE_HOT_LEAVES`), whose layout is `(C, N)` row-major at rest
but `(N, C)` inside lane-major programs — a bare mix with a `(C,)` lane
vector is wrong in one of the two layouts no matter which expansion you
pick, so it must go through the axis-parameterized helpers.

A function-local abstract interpreter propagates signatures through
assignments, arithmetic, `jnp.where`/`minimum`/`maximum`, `TPair`
leaves (`.win`/`.off`), `[:, None]` / `[..., None]` expansions (append a
broadcast-safe "1" axis) and `.T` (reverse). Anything else (slicing,
reductions, kernels) degrades to UNKNOWN — the pass only flags when BOTH
sides of an operator carry known, incompatible signatures, so it is
quiet by construction and loud exactly on the seeded bug class.

Waive a deliberate mix with `# ktpu: shape-ok(<reason>)`.
Scope: simulation-path modules (lint.SIM_MODULES or `# ktpu: sim-path`).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from kubernetriks_tpu.lint import (
    LintContext,
    SourceFile,
    Violation,
    dotted_name,
    is_sim_path,
)

PASS_ID = "shapecontract"

REGISTRY_NAME = "AXIS_SIGNATURES"

# A signature: (tokens, origin leaf name). tokens == ("@node",) marks the
# layout-ambiguous lane-major leaves.
Sig = Tuple[Tuple[str, ...], str]

_NEUTRAL_ATTRS = {"shape", "dtype", "ndim", "size", "sharding", "at"}
_PAIR_ATTRS = {"win", "off"}
# 2-arg elementwise combiners whose operands must already broadcast.
_COMBINE_CALLS = {
    "where",
    "minimum",
    "maximum",
    "add",
    "subtract",
    "multiply",
    "logical_and",
    "logical_or",
    "t_le",
    "t_lt",
    "t_ge",
    "t_gt",
    "t_eq",
    "t_add",
    "t_sub",
    "t_where",
    "t_min",
    "t_max",
}
# receiver-preserving methods: sig(x.m(...)) == sig(x)
_PRESERVE_METHODS = {"astype", "copy", "clip"}
_PRESERVE_CALLS = {"asarray", "abs", "negative", "logical_not", "copy"}


def collect_signatures(ctx: LintContext) -> Dict[str, Tuple[str, ...]]:
    """Merge every in-scope AXIS_SIGNATURES dict literal (str -> str)."""
    out: Dict[str, Tuple[str, ...]] = {}
    for sf in ctx.files:
        if not isinstance(sf.tree, ast.Module):
            continue
        for node in sf.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == REGISTRY_NAME
                and isinstance(node.value, ast.Dict)
            ):
                for key, val in zip(node.value.keys, node.value.values):
                    if (
                        isinstance(key, ast.Constant)
                        and isinstance(key.value, str)
                        and isinstance(val, ast.Constant)
                        and isinstance(val.value, str)
                    ):
                        out[key.value] = tuple(
                            t.strip() for t in val.value.split(",")
                        )
    return out


def _compatible(a: Tuple[str, ...], b: Tuple[str, ...]) -> bool:
    """NumPy-style right-aligned axis compatibility over declared tokens:
    tokens agree when equal, or either is "1" (explicit expansion) or "*"
    (declared-unknown). A SHORTER operand is fine when its tokens match
    the longer one's trailing axes — that is the broadcast the authors
    meant; a leading-axis match against a trailing mismatch is the bug."""
    if a == ("@node",) or b == ("@node",):
        # @node vs @node is fine (same layout either way); @node vs a
        # known lane vector is the lane-major hazard, handled by caller.
        return a == b
    for ta, tb in zip(reversed(a), reversed(b)):
        if ta == tb or ta in ("1", "*") or tb in ("1", "*"):
            continue
        return False
    return True


class _Checker:
    def __init__(
        self,
        sf: SourceFile,
        fn: ast.FunctionDef,
        registry: Dict[str, Tuple[str, ...]],
        violations: List[Violation],
    ):
        self.sf = sf
        self.fn = fn
        self.registry = registry
        self.violations = violations
        self.env: Dict[str, Sig] = {}

    # -- signature inference -------------------------------------------------

    def sig(self, node: ast.AST) -> Optional[Sig]:
        if isinstance(node, ast.Attribute):
            if node.attr in self.registry:
                return (self.registry[node.attr], node.attr)
            if node.attr in _PAIR_ATTRS:
                return self.sig(node.value)  # TPair leaves share its shape
            if node.attr == "T":
                base = self.sig(node.value)
                if base is not None and base[0] != ("@node",):
                    return (tuple(reversed(base[0])), base[1])
                return base
            return None
        if isinstance(node, ast.Name):
            return self.env.get(node.id)
        if isinstance(node, ast.Constant):
            if isinstance(node.value, (int, float, bool, complex)):
                return ((), "scalar")
            return None
        if isinstance(node, ast.UnaryOp):
            return self.sig(node.operand)
        if isinstance(node, ast.BinOp):
            return self._combine(node, self.sig(node.left), self.sig(node.right))
        if isinstance(node, ast.Compare):
            s = self.sig(node.left)
            for comp in node.comparators:
                s = self._combine(node, s, self.sig(comp))
            return s
        if isinstance(node, ast.BoolOp):
            s: Optional[Sig] = None
            for v in node.values:
                s = self._combine(node, s, self.sig(v))
            return s
        if isinstance(node, ast.IfExp):
            return self._combine(node, self.sig(node.body), self.sig(node.orelse))
        if isinstance(node, ast.Subscript):
            return self._subscript_sig(node)
        if isinstance(node, ast.Call):
            return self._call_sig(node)
        return None

    def _subscript_sig(self, node: ast.Subscript) -> Optional[Sig]:
        base = self.sig(node.value)
        if base is None or base[0] == ("@node",):
            return None
        sl = node.slice
        elts = list(sl.elts) if isinstance(sl, ast.Tuple) else [sl]
        # x[:, None] / x[..., None] style: full slices / Ellipsis keep
        # axes, None inserts a broadcast-safe "1". Anything else (index,
        # bounded slice, mask) -> unknown.
        tokens = list(base[0])
        out: List[str] = []
        pos = 0
        for e in elts:
            if isinstance(e, ast.Constant) and e.value is None:
                out.append("1")
            elif isinstance(e, ast.Slice) and (
                e.lower is None and e.upper is None and e.step is None
            ):
                if pos >= len(tokens):
                    return None
                out.append(tokens[pos])
                pos += 1
            elif isinstance(e, ast.Constant) and e.value is Ellipsis:
                take = len(tokens) - pos - sum(
                    1
                    for r in elts[elts.index(e) + 1 :]
                    if not (isinstance(r, ast.Constant) and r.value is None)
                )
                out.extend(tokens[pos : pos + max(take, 0)])
                pos += max(take, 0)
            else:
                return None
        out.extend(tokens[pos:])
        return (tuple(out), base[1])

    def _call_sig(self, node: ast.Call) -> Optional[Sig]:
        fname = dotted_name(node.func)
        bare = fname.rsplit(".", 1)[-1] if fname else None
        if bare in _COMBINE_CALLS:
            s: Optional[Sig] = None
            for a in node.args:
                s = self._combine(node, s, self.sig(a))
            return s
        if bare in _PRESERVE_CALLS and len(node.args) >= 1:
            return self.sig(node.args[0])
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _PRESERVE_METHODS
        ):
            return self.sig(node.func.value)
        if bare == "TPair":
            s = None
            for kw in node.keywords:
                s = self._combine(node, s, self.sig(kw.value))
            for a in node.args:
                s = self._combine(node, s, self.sig(a))
            return s
        return None

    def _combine(
        self, node: ast.AST, a: Optional[Sig], b: Optional[Sig]
    ) -> Optional[Sig]:
        """Combine two operand signatures, flagging incompatible known
        pairs. Returns the broader signature (or None when unknown)."""
        if a is None:
            return b
        if b is None:
            return a
        ta, tb = a[0], b[0]
        if ta == ():
            return b
        if tb == ():
            return a
        if ta == ("@node",) or tb == ("@node",):
            if ta == tb:
                return a
            other, node_side = (b, a) if ta == ("@node",) else (a, b)
            if other[0] == ("C",):
                self._flag(
                    node,
                    f"lane-major-ambiguous node leaf '{node_side[1]}' "
                    f"meets per-cluster (C,) leaf '{other[1]}' directly — "
                    "the broadcast axis depends on KTPU_LANE_MAJOR; route "
                    "the mix through the axis-parameterized helpers (or "
                    "an explicit transpose/broadcast)",
                )
            return None
        if not _compatible(ta, tb):
            sa = "(" + ",".join(ta) + ("," if len(ta) == 1 else "") + ")"
            sb = "(" + ",".join(tb) + ("," if len(tb) == 1 else "") + ")"
            self._flag(
                node,
                f"{sa} expression from '{a[1]}' meets {sb} expression "
                f"from '{b[1]}' without an explicit [:, None] / "
                "transpose / broadcast_to — the per-cluster lane axis "
                "would broadcast on the wrong axis (the PR 13 tolerance "
                "bug class)",
            )
            return None
        # the broader (higher-rank) signature wins
        return a if len(ta) >= len(tb) else b

    # -- violations ----------------------------------------------------------

    def _flag(self, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", self.fn.lineno)
        if self.sf.waived(line, PASS_ID):
            return
        v = Violation(
            self.sf.path,
            line,
            PASS_ID,
            f"{message}; waive a deliberate mix with "
            "# ktpu: shape-ok(reason)",
        )
        if v not in self.violations:
            self.violations.append(v)

    # -- walk ----------------------------------------------------------------

    def run(self) -> None:
        self.visit_stmts(self.fn.body)

    def visit_stmts(self, stmts) -> None:
        for st in stmts:
            self.visit_stmt(st)

    def visit_stmt(self, st: ast.stmt) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        for _, value in ast.iter_fields(st):
            if isinstance(value, ast.expr):
                self.sig(value)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.expr):
                        self.sig(v)
                    elif isinstance(v, ast.stmt):
                        self.visit_stmt(v)
                    elif isinstance(v, ast.excepthandler):
                        self.visit_stmts(v.body)
        if isinstance(st, ast.Assign):
            s = self.sig(st.value)
            for tgt in st.targets:
                if isinstance(tgt, ast.Name):
                    if s is not None:
                        self.env[tgt.id] = s
                    else:
                        self.env.pop(tgt.id, None)
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            if isinstance(st.target, ast.Name):
                s = self.sig(st.value)
                if s is not None:
                    self.env[st.target.id] = s
                else:
                    self.env.pop(st.target.id, None)
        elif isinstance(st, ast.AugAssign):
            self._combine(st, self.sig(st.target), self.sig(st.value))


def check(ctx: LintContext) -> List[Violation]:
    registry = collect_signatures(ctx)
    if not registry:
        return []
    violations: List[Violation] = []
    for sf in ctx.files:
        if not is_sim_path(sf):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _Checker(sf, node, registry, violations).run()
    return violations
