"""Feeder-lock discipline pass: threaded modules share state under the
lock, and never block while holding it.

`batched/stream.py` runs a producer THREAD against the engine thread,
sharing a slab ring plus a dozen counters through one condition
variable. The invariants that keep it correct are exactly the ones
nothing was checking:

1. every instance attribute MUTATED outside `__init__` (the shared
   mutable set — attributes only written in `__init__` are thread-safe
   configuration and exempt) is read and written ONLY inside a
   `with self.<lock>:` block, unless it is declared in an explicit
   class-level `_LOCK_FREE` handoff tuple (with the reason in a
   comment) or line-waived;
2. no blocking call while HOLDING the lock: `time.sleep`, `.join()`,
   `jax.block_until_ready` and `.wait()` on anything that is not the
   lock itself (a condvar `self._cond.wait()` releases the lock while
   waiting — that one is the point) would stall both threads.

Lock attributes are discovered, not configured: any `self.X =
threading.Condition/Lock/RLock(...)` in `__init__`. Classes without one
are skipped (nothing to hold). `__init__` is exempt end to end — it runs
before the thread starts (starting the thread is its last act by
convention; a violation of THAT convention shows up as an unlocked
write from the producer body instead).

Waive with `# ktpu: lock-ok(<reason>)`.
Scope: `batched/stream.py` and any module carrying `# ktpu: threaded`.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from kubernetriks_tpu.lint import (
    LintContext,
    SourceFile,
    Violation,
    dotted_name,
    is_threaded,
)

PASS_ID = "feederlock"

_LOCK_CTORS = {"Condition", "Lock", "RLock"}
_BLOCKING_BARE = {"sleep", "join", "block_until_ready"}
# In-place container mutation counts as a write (`self._ring.append(..)`)
_MUTATOR_METHODS = {
    "append",
    "appendleft",
    "pop",
    "popleft",
    "extend",
    "clear",
    "add",
    "remove",
    "discard",
    "update",
    "insert",
}
HANDOFF_CONST = "_LOCK_FREE"


def _self_attr(node: ast.AST) -> Optional[str]:
    """'X' for a one-level self.X attribute access, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    locks: Set[str] = set()
    for method in cls.body:
        if not isinstance(method, ast.FunctionDef) or method.name != "__init__":
            continue
        for node in ast.walk(method):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                fname = dotted_name(node.value.func) or ""
                if fname.rsplit(".", 1)[-1] in _LOCK_CTORS:
                    for tgt in node.targets:
                        attr = _self_attr(tgt)
                        if attr is not None:
                            locks.add(attr)
    return locks


def _handoff(cls: ast.ClassDef) -> Set[str]:
    for node in cls.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == HANDOFF_CONST
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            return {
                e.value
                for e in node.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            }
    return set()


class _Touch:
    __slots__ = ("attr", "line", "locked", "write", "method")

    def __init__(self, attr, line, locked, write, method):
        self.attr = attr
        self.line = line
        self.locked = locked
        self.write = write
        self.method = method


class _MethodWalker:
    """Collects self-attribute touches with lock context, and flags
    blocking calls made while the lock is held."""

    def __init__(
        self,
        sf: SourceFile,
        method: ast.FunctionDef,
        locks: Set[str],
        touches: List[_Touch],
        violations: List[Violation],
    ):
        self.sf = sf
        self.method = method
        self.locks = locks
        self.touches = touches
        self.violations = violations

    def run(self) -> None:
        self._visit_stmts(self.method.body, locked=False)

    def _is_lock_expr(self, node: ast.AST) -> bool:
        attr = _self_attr(node)
        return attr is not None and attr in self.locks

    def _visit_stmts(self, stmts, locked: bool) -> None:
        for st in stmts:
            self._visit_stmt(st, locked)

    def _visit_stmt(self, st: ast.stmt, locked: bool) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested defs run later, outside this lock scope
        if isinstance(st, (ast.With, ast.AsyncWith)):
            inner = locked
            for item in st.items:
                self._scan_expr(item.context_expr, locked, writes=False)
                if self._is_lock_expr(item.context_expr):
                    inner = True
            self._visit_stmts(st.body, inner)
            return
        # compound statements: scan their own expressions, then bodies
        for field, value in ast.iter_fields(st):
            if isinstance(value, ast.expr):
                self._scan_expr(
                    value,
                    locked,
                    writes=field in ("target", "targets"),
                )
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.expr):
                        writes = (
                            isinstance(st, (ast.Assign, ast.Delete))
                            and field == "targets"
                        )
                        self._scan_expr(v, locked, writes=writes)
                    elif isinstance(v, ast.stmt):
                        self._visit_stmt(v, locked)
                    elif isinstance(v, ast.excepthandler):
                        self._visit_stmts(v.body, locked)

    def _scan_expr(self, node: ast.AST, locked: bool, writes: bool) -> None:
        for sub in ast.walk(node):
            # `self.X[i] = v` / `del self.X[i]` / `del self.X`: the inner
            # Attribute carries Load ctx, but the containing Store/Del
            # Subscript (or the Delete target itself) mutates the attr.
            if writes and isinstance(sub, ast.Subscript) and isinstance(
                sub.ctx, (ast.Store, ast.Del)
            ):
                recv = _self_attr(sub.value)
                if recv is not None and recv not in self.locks:
                    self.touches.append(
                        _Touch(recv, sub.lineno, locked, True, self.method.name)
                    )
            attr = _self_attr(sub)
            if attr is not None and attr not in self.locks:
                is_write = writes and isinstance(
                    getattr(sub, "ctx", None), (ast.Store, ast.Del)
                )
                self.touches.append(
                    _Touch(
                        attr,
                        sub.lineno,
                        locked,
                        is_write,
                        self.method.name,
                    )
                )
            # self.X.append(...) style in-place mutation is a write too
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _MUTATOR_METHODS
            ):
                recv = _self_attr(sub.func.value)
                if recv is not None and recv not in self.locks:
                    self.touches.append(
                        _Touch(recv, sub.lineno, locked, True, self.method.name)
                    )
            if locked and isinstance(sub, ast.Call):
                self._check_blocking(sub)

    def _check_blocking(self, call: ast.Call) -> None:
        fname = dotted_name(call.func)
        bare = fname.rsplit(".", 1)[-1] if fname else None
        blocking = bare in _BLOCKING_BARE
        if (
            not blocking
            and isinstance(call.func, ast.Attribute)
            and call.func.attr in ("wait", "acquire")
            and not self._is_lock_expr(call.func.value)
        ):
            blocking = True
        if blocking and not self.sf.waived(call.lineno, PASS_ID):
            self.violations.append(
                Violation(
                    self.sf.path,
                    call.lineno,
                    PASS_ID,
                    f"blocking call ({fname or call.func.attr}) while "
                    "HOLDING the ring lock — both threads stall (the "
                    "condvar's own .wait() releases it and is the one "
                    "legal wait); move the wait outside the with block, "
                    "or waive with # ktpu: lock-ok(reason)",
                )
            )


def _check_class(
    sf: SourceFile, cls: ast.ClassDef, violations: List[Violation]
) -> None:
    locks = _lock_attrs(cls)
    if not locks:
        return
    handoff = _handoff(cls)
    touches: List[_Touch] = []
    for method in cls.body:
        if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        _MethodWalker(sf, method, locks, touches, violations).run()
    # Shared-mutable set: attributes WRITTEN outside __init__.
    shared = {
        t.attr
        for t in touches
        if t.write and t.method != "__init__"
    }
    shared -= handoff
    for t in touches:
        if (
            t.attr in shared
            and t.method != "__init__"
            and not t.locked
            and not sf.waived(t.line, PASS_ID)
        ):
            kind = "write to" if t.write else "read of"
            violations.append(
                Violation(
                    sf.path,
                    t.line,
                    PASS_ID,
                    f"unlocked {kind} shared attribute self.{t.attr} in "
                    f"{cls.name}.{t.method} (mutated off-thread) — touch "
                    f"it under `with self.{sorted(locks)[0]}:`, declare "
                    f"it in {cls.name}.{HANDOFF_CONST} with the handoff "
                    "story, or waive with # ktpu: lock-ok(reason)",
                )
            )


def check(ctx: LintContext) -> List[Violation]:
    violations: List[Violation] = []
    for sf in ctx.files:
        if not is_threaded(sf):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                _check_class(sf, node, violations)
    return violations
