"""Scenario-trace discipline pass: the fleet's compile-once guarantee,
statically.

`ScenarioFleet` serves heterogeneous what-if configs through ONE compiled
engine because every scenario-bearing parameter is per-cluster (C,)
TRACED data (`fleet.scenario_leaves` composes them; `engine.
update_scenario` re-installs them as host->device puts). That guarantee
dies silently the moment a scenario leaf flows into anything that shapes
a program: Python control flow, an `int()`/`.item()` host cast, a
`static_argnames` kwarg of a jit entry, or a shape expression — the next
wave then recompiles (or worse, compiles the previous wave's config into
the program). bench --sweep catches the regression at runtime via
jit-cache counts; this pass catches it at commit time, naming the leaf.

Sources: attribute reads of the registered traced leaves — the
`SCENARIO_TRACED_LEAVES` manifest next to `AutoscaleStatics`
(batched/autoscale.py) plus `StepConstants.fault_seed`
(`SCENARIO_TRACED_CONSTS` in batched/state.py). The pass unions every
in-scope manifest with the built-in defaults, so fixtures and future
registries extend it without touching the pass.

Sinks (function-local taint, the hostsync machinery's sibling):
- `if`/`while`/`assert` tests and `for` iterables;
- `int()` / `float()` / `bool()` casts and `.item()` reads;
- shape positions: `jnp.zeros/ones/full/empty/arange(shape..)`,
  `jnp.broadcast_to(x, shape)`'s shape argument, `.reshape(...)` args;
- keyword arguments that are `static_argnames` of a known jit entry.

`x is None` / `is not None` presence checks never flag (leaf presence is
a legitimate structural static — the `auto`/`fault_seed` pattern). Waive
a deliberate host read with `# ktpu: scenario-ok(<reason>)`.

Scope: simulation-path modules (lint.SIM_MODULES or `# ktpu: sim-path`).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from kubernetriks_tpu.lint import (
    LintContext,
    SourceFile,
    Violation,
    dotted_name,
    is_sim_path,
)

PASS_ID = "scenariotrace"

# Built-in defaults so PARTIAL-scope lints (one changed file, without
# autoscale.py/state.py in scope) keep their taint sources; unioned with
# every in-scope SCENARIO_TRACED_LEAVES / SCENARIO_TRACED_CONSTS manifest
# (kept in the modules that own the leaves, so the registry lives next to
# the NamedTuple it describes). This copy is pinned EQUAL to those
# manifests by tests/test_lint.py::test_stateleaf_registries_match_runtime
# — rename a leaf in one place and CI names the drift.
DEFAULT_TRACED = frozenset(
    {
        # AutoscaleStatics per-lane control-law leaves (fleet-composed)
        "hpa_interval",
        "hpa_tolerance",
        "ca_threshold",
        "ca_max_nodes",
        "pg_active_from",
        "d_hpa_up",
        "d_hpa_down",
        "d_ca_up",
        "d_ca_down",
        "ca_period",
        "ca_snap",
        "ca_finish_vis",
        "ca_commit_vis",
        # StepConstants per-lane fault seed
        "fault_seed",
        # StepConstants lane-async window clocks (engine set_lane_plan
        # re-seeds a finished lane as a pure data update — compile-once)
        "lane_clock",
        "lane_horizon",
    }
)
MANIFEST_NAMES = ("SCENARIO_TRACED_LEAVES", "SCENARIO_TRACED_CONSTS")

_NEUTRAL_ATTRS = {"shape", "dtype", "ndim", "size", "sharding"}
_CAST_FUNCS = {"int", "float", "bool"}
_NEUTRAL_FUNCS = {"hasattr", "isinstance", "len", "getattr", "type", "id"}
# callee bare name -> indices of its SHAPE-position arguments
_SHAPE_ARGS: Dict[str, Tuple[int, ...]] = {
    "zeros": (0,),
    "ones": (0,),
    "empty": (0,),
    "full": (0,),
    "arange": (0, 1, 2),
    "broadcast_to": (1,),
    "iota": (1,),
}


def _collect_traced(ctx: LintContext) -> frozenset:
    names: Set[str] = set(DEFAULT_TRACED)
    for sf in ctx.files:
        if not isinstance(sf.tree, ast.Module):
            continue
        for node in sf.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id in MANIFEST_NAMES
                and isinstance(node.value, (ast.Tuple, ast.List))
            ):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str
                    ):
                        names.add(elt.value)
    return frozenset(names)


class _Checker:
    def __init__(
        self,
        sf: SourceFile,
        fn: ast.FunctionDef,
        traced: frozenset,
        statics_by_entry: Dict[str, frozenset],
        violations: List[Violation],
    ):
        self.sf = sf
        self.fn = fn
        self.traced = traced
        self.statics_by_entry = statics_by_entry
        self.violations = violations
        self.tainted: Set[str] = set()

    # -- taint ---------------------------------------------------------------

    def _leaf_of(self, node: ast.AST) -> str:
        """Best-effort leaf name for the message."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and sub.attr in self.traced:
                return sub.attr
        return "scenario leaf"

    def _is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Attribute):
            if node.attr in _NEUTRAL_ATTRS:
                return False
            if node.attr in self.traced:
                return True
            return self._is_tainted(node.value)
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname is not None:
                bare = fname.rsplit(".", 1)[-1]
                if bare in _CAST_FUNCS or bare in _NEUTRAL_FUNCS:
                    return False  # casts are flagged as sinks, not sources
            # traced data stays traced through array ops / helpers —
            # including method calls on tainted receivers (.sum(), .any())
            if isinstance(node.func, ast.Attribute) and node.func.attr not in (
                "item",
            ):
                if self._is_tainted(node.func.value):
                    return True
            return any(
                self._is_tainted(a) for a in node.args
            ) or any(self._is_tainted(kw.value) for kw in node.keywords)
        if isinstance(node, ast.Subscript):
            return self._is_tainted(node.value)
        if isinstance(node, ast.BinOp):
            return self._is_tainted(node.left) or self._is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._is_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self._is_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False  # presence checks are structural statics
            return self._is_tainted(node.left) or any(
                self._is_tainted(c) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return self._is_tainted(node.body) or self._is_tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self._is_tainted(node.value)
        return False

    # -- violations ----------------------------------------------------------

    def _flag(self, node: ast.AST, leaf: str, what: str) -> None:
        if self.sf.waived(node.lineno, PASS_ID):
            return
        self.violations.append(
            Violation(
                self.sf.path,
                node.lineno,
                PASS_ID,
                f"per-lane scenario leaf '{leaf}' flows into {what} — a "
                "what-if config would shape the compiled program and the "
                "fleet's compile-once guarantee breaks (recompile per "
                "wave); keep scenario leaves traced, or waive a "
                "deliberate host read with # ktpu: scenario-ok(reason)",
            )
        )

    def _check_expr(self, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fname = dotted_name(sub.func)
            bare = fname.rsplit(".", 1)[-1] if fname else None
            if (
                bare in _CAST_FUNCS
                and len(sub.args) == 1
                and self._is_tainted(sub.args[0])
            ):
                self._flag(
                    sub,
                    self._leaf_of(sub.args[0]),
                    f"a host {bare}() cast",
                )
                continue
            if (
                isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "item"
                and not sub.args
                and self._is_tainted(sub.func.value)
            ):
                self._flag(sub, self._leaf_of(sub.func.value), "an .item() read")
                continue
            # shape-position arguments
            shape_idx: Tuple[int, ...] = ()
            if bare in _SHAPE_ARGS:
                shape_idx = _SHAPE_ARGS[bare]
            elif isinstance(sub.func, ast.Attribute) and sub.func.attr == "reshape":
                shape_idx = tuple(range(len(sub.args)))
            for i in shape_idx:
                if i < len(sub.args) and self._is_tainted(sub.args[i]):
                    self._flag(
                        sub,
                        self._leaf_of(sub.args[i]),
                        f"a shape expression ({bare or 'reshape'} arg {i})",
                    )
            # static kwargs of known jit entries
            if bare in self.statics_by_entry:
                statics = self.statics_by_entry[bare]
                for kw in sub.keywords:
                    if kw.arg in statics and self._is_tainted(kw.value):
                        self._flag(
                            kw.value,
                            self._leaf_of(kw.value),
                            f"jit static {kw.arg!r} of entry {bare}",
                        )

    # -- walk ----------------------------------------------------------------

    def run(self) -> None:
        self.visit_stmts(self.fn.body)

    def visit_stmts(self, stmts) -> None:
        for st in stmts:
            self.visit_stmt(st)

    def visit_stmt(self, st: ast.stmt) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(st, (ast.If, ast.While)):
            self._check_expr(st.test)
            if self._is_tainted(st.test):
                self._flag(
                    st, self._leaf_of(st.test), "Python control flow"
                )
            for body in (st.body, st.orelse):
                self.visit_stmts(body)
            return
        if isinstance(st, ast.Assert):
            self._check_expr(st.test)
            if self._is_tainted(st.test):
                self._flag(st, self._leaf_of(st.test), "a Python assert")
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._check_expr(st.iter)
            if self._is_tainted(st.iter):
                self._flag(st, self._leaf_of(st.iter), "Python iteration")
            self.visit_stmts(st.body)
            self.visit_stmts(st.orelse)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._check_expr(item.context_expr)
            self.visit_stmts(st.body)
            return
        if isinstance(st, ast.Try):
            self.visit_stmts(st.body)
            for handler in st.handlers:
                self.visit_stmts(handler.body)
            self.visit_stmts(st.orelse)
            self.visit_stmts(st.finalbody)
            return
        for _, value in ast.iter_fields(st):
            if isinstance(value, ast.expr):
                self._check_expr(value)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.expr):
                        self._check_expr(v)
        if isinstance(st, ast.Assign):
            tainted = self._is_tainted(st.value)
            for tgt in st.targets:
                elts = (
                    tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) else [tgt]
                )
                for e in elts:
                    path = dotted_name(e)
                    if path is None:
                        continue
                    if tainted:
                        self.tainted.add(path)
                    else:
                        self.tainted.discard(path)
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            path = dotted_name(st.target)
            if path is not None:
                if self._is_tainted(st.value):
                    self.tainted.add(path)
                else:
                    self.tainted.discard(path)
        elif isinstance(st, ast.AugAssign):
            if self._is_tainted(st.value):
                path = dotted_name(st.target)
                if path is not None:
                    self.tainted.add(path)


def check(ctx: LintContext) -> List[Violation]:
    traced = _collect_traced(ctx)
    statics_by_entry: Dict[str, frozenset] = {}
    for entry in ctx.jit_entries:
        if entry.static_argnames:
            statics_by_entry[entry.name] = statics_by_entry.get(
                entry.name, frozenset()
            ) | frozenset(entry.static_argnames)
    violations: List[Violation] = []
    for sf in ctx.files:
        if not is_sim_path(sf):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _Checker(sf, node, traced, statics_by_entry, violations).run()
    return violations
