"""State-leaf coverage pass: every state pytree leaf is provably handled
in every registered consumer.

The bug class (PR 13/14 lived it twice): `ClusterBatchState` /
`AutoscaleState` / `TelemetryRing` leaves ride lane resets, checkpoint
save/restore, state comparison, telemetry stripping and the sanitizer's
consume-donated sweep — but nothing forced a NEW leaf to reach those
consumers. A leaf that misses one silently survives fleet resets (state
bleeds between what-if queries), restores into the wrong structure, or
escapes the parity comparator. PR 14's fix was architectural ("reclaim
counters ride the pytree so fleet resets cover them automatically");
this pass proves that architecture holds for every future leaf.

Mechanism. The state classes are parsed from their NamedTuple AST
definitions (fields = annotated assignments; a `= None` default marks a
STRUCTURAL leaf — presence is part of the compiled program's identity).
Each registered consumer then proves coverage one of three ways:

- pytree-GENERIC traversal: the function body calls `jax.tree.map` /
  `tree_flatten(_with_path)` / `tree_leaves` (or rebuilds through
  `._replace`, which passes unnamed leaves through unchanged) — every
  leaf, present and future, is handled by construction.
- by NAME: every required field name appears in the function body
  (attribute, keyword, or string) — the init-constructor style.
- by MANIFEST: a module-level constant (tuple or dict keys) lists the
  covered leaves with their coverage story — the checkpoint-meta style
  (`engine.CKPT_COVERED_LEAVES`).

Each class also carries a leaf MANIFEST next to its definition
(`CLUSTER_STATE_LEAVES` / `AUTOSCALE_STATE_LEAVES` /
`TELEMETRY_RING_LEAVES`) that must equal the field list exactly — THE
"how to add a state leaf" checklist anchor (DESIGN §7): adding a leaf
without touching the manifest is a lint error pointing at the checklist,
and a stale manifest entry is equally loud. Allocation-index leaves
(structural `ca_*` members of AutoscaleState) must additionally appear
in the DESIGN §12 invariants list — the doc registry.

A `# ktpu: state-module` file pragma marks a self-contained fixture:
classes, manifests and consumer functions are all resolved within that
file (tests/lint_fixtures/stateleaf_*.py).

Waive a deliberate gap with `# ktpu: leaf-ok(<reason>)` on the consumer
def line or the class line.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from kubernetriks_tpu.lint import (
    LintContext,
    SourceFile,
    Violation,
    dotted_name,
)

PASS_ID = "stateleaf"

STATE_PY = "kubernetriks_tpu/batched/state.py"
AUTOSCALE_PY = "kubernetriks_tpu/batched/autoscale.py"
ENGINE_PY = "kubernetriks_tpu/batched/engine.py"
FLEET_PY = "kubernetriks_tpu/batched/fleet.py"
SANITIZE_PY = "kubernetriks_tpu/sanitize.py"

# class name -> defining module (path match is exact on the repo layout;
# a state-module pragma file overrides with its own definitions).
STATE_CLASSES: Dict[str, str] = {
    "ClusterBatchState": STATE_PY,
    "TelemetryRing": STATE_PY,
    "AutoscaleState": AUTOSCALE_PY,
    # Lane-async clock leaves ride StepConstants (traced per-lane data,
    # engine set_lane_plan re-seeds without recompiling) — a new consts
    # leaf must reach the manifest like any state leaf.
    "StepConstants": STATE_PY,
}

# class -> (manifest constant, module holding it)
MANIFESTS: Dict[str, Tuple[str, str]] = {
    "ClusterBatchState": ("CLUSTER_STATE_LEAVES", STATE_PY),
    "TelemetryRing": ("TELEMETRY_RING_LEAVES", STATE_PY),
    "AutoscaleState": ("AUTOSCALE_STATE_LEAVES", AUTOSCALE_PY),
    "StepConstants": ("STEP_CONSTANTS_LEAVES", STATE_PY),
}

CHECKLIST_HINT = (
    "follow the DESIGN §7 'how to add a state leaf' checklist"
)


@dataclass(frozen=True)
class Registry:
    """One registered consumer: `fields` selects which leaves it must
    handle — 'all', 'required' (no default: constructors must name them)
    or 'structural' (`= None` default: presence is program identity, so
    checkpoint meta must record it)."""

    name: str
    path: str
    func: str
    classes: Tuple[str, ...]
    fields: str = "all"  # "all" | "required" | "structural"
    manifest: Optional[str] = None  # module constant instead of the body


CONSUMERS: Tuple[Registry, ...] = (
    Registry(
        "fleet-reset",
        FLEET_PY,
        "_make_reset_lanes",
        ("ClusterBatchState", "AutoscaleState", "TelemetryRing"),
    ),
    Registry(
        "compare-states",
        STATE_PY,
        "compare_states",
        ("ClusterBatchState", "AutoscaleState", "TelemetryRing"),
    ),
    Registry("strip-telemetry", STATE_PY, "strip_telemetry", ("ClusterBatchState",)),
    Registry(
        "sanitize-donated",
        SANITIZE_PY,
        "consume_donated",
        ("ClusterBatchState", "AutoscaleState", "TelemetryRing"),
    ),
    Registry("init-state", STATE_PY, "init_state", ("ClusterBatchState",), "required"),
    Registry(
        "init-autoscale-state",
        AUTOSCALE_PY,
        "init_autoscale_state",
        ("AutoscaleState",),
    ),
    Registry(
        "ckpt-meta",
        ENGINE_PY,
        "save_checkpoint",
        ("ClusterBatchState", "AutoscaleState"),
        "structural",
        manifest="CKPT_COVERED_LEAVES",
    ),
)

# Doc registry: structural allocation-index leaves must appear in the
# DESIGN §12 invariants section (they carry scalar-naming semantics a
# future reader must not discover by bisecting an endurance run).
DESIGN_DOC = os.path.join("docs", "DESIGN.md")
DESIGN_SECTION = "## 12"
DESIGN_CLASS = "AutoscaleState"
DESIGN_PREFIX = "ca_"

_GENERIC_MARKERS = (
    "tree.map",
    "tree_map",
    "tree.leaves",
    "tree_leaves",
    "tree_flatten",
    "tree_flatten_with_path",
    "tree.flatten",
    "tree_all",
)


@dataclass
class StateClass:
    name: str
    sf: SourceFile
    line: int
    fields: Tuple[str, ...]
    structural: Tuple[str, ...]  # fields defaulted to None

    def select(self, which: str) -> Tuple[str, ...]:
        if which == "structural":
            return self.structural
        if which == "required":
            return tuple(
                f for f in self.fields if f not in set(self._defaulted)
            )
        return self.fields

    _defaulted: Tuple[str, ...] = ()


def _class_fields(node: ast.ClassDef) -> Tuple[Tuple[str, ...], Tuple[str, ...], Tuple[str, ...]]:
    """(all fields, structural fields (= None default), any-default fields)
    of a NamedTuple class body."""
    fields: List[str] = []
    structural: List[str] = []
    defaulted: List[str] = []
    for st in node.body:
        if isinstance(st, ast.AnnAssign) and isinstance(st.target, ast.Name):
            fields.append(st.target.id)
            if st.value is not None:
                defaulted.append(st.target.id)
                if isinstance(st.value, ast.Constant) and st.value.value is None:
                    structural.append(st.target.id)
    return tuple(fields), tuple(structural), tuple(defaulted)


def _is_namedtuple(node: ast.ClassDef) -> bool:
    for base in node.bases:
        name = dotted_name(base) or ""
        if name.rsplit(".", 1)[-1] == "NamedTuple":
            return True
    return False


def _find_classes(files, fixture: Optional[SourceFile]) -> Dict[str, StateClass]:
    out: Dict[str, StateClass] = {}
    scope = [fixture] if fixture is not None else files
    for sf in scope:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef) or not _is_namedtuple(node):
                continue
            if node.name not in STATE_CLASSES:
                continue
            if fixture is None and sf.path != STATE_CLASSES[node.name]:
                continue
            fields, structural, defaulted = _class_fields(node)
            sc = StateClass(node.name, sf, node.lineno, fields, structural)
            sc._defaulted = defaulted
            out[node.name] = sc
    return out


def _find_func(sf: SourceFile, name: str) -> Optional[ast.FunctionDef]:
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == name:
                return node
    return None


def _has_generic_traversal(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname is not None and (
                fname.endswith(_GENERIC_MARKERS)
                or fname.startswith(("jax.tree", "tree_util."))
            ):
                return True
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "_replace"
            ):
                # NamedTuple._replace passes every unnamed leaf through
                # unchanged — structure-preserving by construction.
                return True
    return False


def _body_tokens(fn: ast.AST) -> Set[str]:
    """Every identifier-ish token in a function body: attribute names,
    bare names, keyword-argument names, string constants."""
    tokens: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute):
            tokens.add(node.attr)
        elif isinstance(node, ast.Name):
            tokens.add(node.id)
        elif isinstance(node, ast.keyword) and node.arg:
            tokens.add(node.arg)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            tokens.add(node.value)
    return tokens


def _module_const_names(
    sf: SourceFile, const: str
) -> Tuple[Optional[Set[str]], Optional[int]]:
    """Names listed by a module-level manifest constant: a tuple/list of
    strings, or a dict with string keys (values = coverage reasons)."""
    if not isinstance(sf.tree, ast.Module):
        return None, None
    for node in sf.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == const
        ):
            val = node.value
            names: Set[str] = set()
            if isinstance(val, (ast.Tuple, ast.List)):
                for elt in val.elts:
                    if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str
                    ):
                        names.add(elt.value)
                    else:
                        return None, node.lineno
                return names, node.lineno
            if isinstance(val, ast.Dict):
                for key in val.keys:
                    if isinstance(key, ast.Constant) and isinstance(
                        key.value, str
                    ):
                        names.add(key.value)
                    else:
                        return None, node.lineno
                return names, node.lineno
            return None, node.lineno
    return None, None


def _check_consumer(
    reg: Registry,
    sf: SourceFile,
    classes: Dict[str, StateClass],
    out: List[Violation],
) -> None:
    # Manifest-backed registry: the constant's keys are the coverage.
    if reg.manifest is not None:
        names, line = _module_const_names(sf, reg.manifest)
        anchor = line or 1
        if names is None:
            out.append(
                Violation(
                    sf.path,
                    anchor,
                    PASS_ID,
                    f"registry '{reg.name}': manifest constant "
                    f"{reg.manifest} missing or not a literal tuple/dict "
                    f"of leaf names in {sf.path}",
                )
            )
            return
        wanted: Set[str] = set()
        resolved_all = all(cls in classes for cls in reg.classes)
        for cls in reg.classes:
            sc = classes.get(cls)
            if sc is None:
                continue
            for leaf in sc.select(reg.fields):
                wanted.add(leaf)
                if leaf not in names and not sf.waived(anchor, PASS_ID):
                    out.append(
                        Violation(
                            sf.path,
                            anchor,
                            PASS_ID,
                            f"state leaf {cls}.{leaf} is not covered by "
                            f"registry '{reg.name}' ({reg.manifest}) — "
                            f"record how checkpoint save/restore handles "
                            f"it, or {CHECKLIST_HINT}",
                        )
                    )
        # Staleness is only judgeable when EVERY registered class resolved
        # in scope — a partial lint (one changed file) must not demand the
        # deletion of entries covering the out-of-scope classes.
        if resolved_all:
            for name in sorted(names - wanted):
                if not sf.waived(anchor, PASS_ID):
                    out.append(
                        Violation(
                            sf.path,
                            anchor,
                            PASS_ID,
                            f"registry '{reg.name}': {reg.manifest} lists "
                            f"{name!r}, which is not a "
                            f"{'/'.join(reg.classes)} {reg.fields} leaf — "
                            "remove the stale entry",
                        )
                    )
        return
    fn = _find_func(sf, reg.func)
    if fn is None:
        out.append(
            Violation(
                sf.path,
                1,
                PASS_ID,
                f"registered state-leaf consumer {reg.func} (registry "
                f"'{reg.name}') not found in {sf.path} — update the "
                "stateleaf registry if it moved or was renamed",
            )
        )
        return
    if _has_generic_traversal(fn):
        return  # every leaf handled by construction
    tokens = _body_tokens(fn)
    for cls in reg.classes:
        sc = classes.get(cls)
        if sc is None:
            continue
        for leaf in sc.select(reg.fields):
            if leaf not in tokens and not sf.waived(fn.lineno, PASS_ID):
                out.append(
                    Violation(
                        sf.path,
                        fn.lineno,
                        PASS_ID,
                        f"state leaf {cls}.{leaf} is not handled in "
                        f"registry '{reg.name}' ({reg.func}): no "
                        "pytree-generic traversal and the leaf is never "
                        f"named — handle it or {CHECKLIST_HINT}",
                    )
                )


def _check_manifest(
    cls: StateClass, sf: SourceFile, const: str, out: List[Violation]
) -> None:
    names, line = _module_const_names(sf, const)
    if names is None:
        out.append(
            Violation(
                sf.path,
                line or cls.line,
                PASS_ID,
                f"leaf manifest {const} for {cls.name} missing or not a "
                f"literal tuple of strings in {sf.path} — the manifest is "
                f"the 'how to add a state leaf' checklist anchor",
            )
        )
        return
    for leaf in cls.fields:
        if leaf not in names and not sf.waived(cls.line, PASS_ID):
            out.append(
                Violation(
                    sf.path,
                    cls.line,
                    PASS_ID,
                    f"new state leaf {cls.name}.{leaf} is missing from "
                    f"{const} — {CHECKLIST_HINT} (fleet reset, ckpt meta, "
                    "compare_states, sanitize, DESIGN §12 if "
                    "allocation-indexed), then add it to the manifest",
                )
            )
    for name in sorted(names - set(cls.fields)):
        out.append(
            Violation(
                sf.path,
                line,
                PASS_ID,
                f"{const} lists {name!r}, which is not a field of "
                f"{cls.name} — remove the stale manifest entry",
            )
        )


def _check_design_doc(
    classes: Dict[str, StateClass], root: str, out: List[Violation]
) -> None:
    sc = classes.get(DESIGN_CLASS)
    if sc is None or sc.sf.path != STATE_CLASSES[DESIGN_CLASS]:
        return  # only meaningful against the real tree
    doc_path = os.path.join(root, DESIGN_DOC)
    if not os.path.exists(doc_path):
        return  # partial checkout; the docs job lints from the repo root
    with open(doc_path, encoding="utf-8") as fh:
        text = fh.read()
    start = text.find(f"\n{DESIGN_SECTION}")
    if start < 0:
        out.append(
            Violation(
                sc.sf.path,
                sc.line,
                PASS_ID,
                f"registry 'design-s12': section {DESIGN_SECTION!r} not "
                f"found in {DESIGN_DOC} — the allocation-index invariants "
                "list moved; update the stateleaf pass",
            )
        )
        return
    end = text.find("\n## ", start + 1)
    section = text[start : end if end > 0 else len(text)]
    for leaf in sc.structural:
        if not leaf.startswith(DESIGN_PREFIX):
            continue
        if leaf not in section and not sc.sf.waived(sc.line, PASS_ID):
            out.append(
                Violation(
                    sc.sf.path,
                    sc.line,
                    PASS_ID,
                    f"allocation-index leaf {DESIGN_CLASS}.{leaf} is not "
                    f"documented in the {DESIGN_DOC} {DESIGN_SECTION} "
                    "invariants list (registry 'design-s12') — name-order "
                    "semantics must be written down where the reclaim "
                    "protocol lives",
                )
            )


def _root_of(sf: SourceFile) -> str:
    # abspath ends with the repo-relative path; the prefix is the root.
    suffix = sf.path.replace("/", os.sep)
    ap = sf.abspath
    return ap[: -len(suffix)].rstrip(os.sep) if ap.endswith(suffix) else ""


def check(ctx: LintContext) -> List[Violation]:
    out: List[Violation] = []
    by_path = {sf.path: sf for sf in ctx.files}

    # Self-contained fixture modules: classes + consumers in one file.
    fixtures = [sf for sf in ctx.files if "state-module" in sf.pragmas]
    for sf in fixtures:
        classes = _find_classes(ctx.files, fixture=sf)
        if not classes:
            continue
        for cls, (const, _) in MANIFESTS.items():
            if cls in classes:
                _check_manifest(classes[cls], sf, const, out)
        for reg in CONSUMERS:
            if reg.manifest is not None:
                if _module_const_names(sf, reg.manifest)[1] is not None:
                    _check_consumer(reg, sf, classes, out)
                continue
            if _find_func(sf, reg.func) is not None:
                _check_consumer(reg, sf, classes, out)

    # The real tree: classes at their canonical paths, consumers at theirs.
    classes = _find_classes(
        [sf for sf in ctx.files if "state-module" not in sf.pragmas], None
    )
    if classes:
        for cls, sc in classes.items():
            const, path = MANIFESTS[cls]
            holder = by_path.get(path)
            if holder is not None:
                _check_manifest(sc, holder, const, out)
        for reg in CONSUMERS:
            sf = by_path.get(reg.path)
            if sf is None:
                continue  # consumer module out of scope (partial lint)
            if not any(c in classes for c in reg.classes):
                continue
            _check_consumer(reg, sf, classes, out)
        any_sc = next(iter(classes.values()))
        _check_design_doc(classes, _root_of(any_sc.sf), out)
    return out
