"""Central registry of KTPU_* / KUBERNETRIKS_* environment flags.

Every environment flag the framework reads is declared here — name, type,
default, and documentation — and every read goes through the typed helpers
below. This is enforced by the env-flag lint pass
(kubernetriks_tpu/lint/envflags.py): an `os.environ` / `os.getenv` read of a
KTPU_*/KUBERNETRIKS_* name anywhere outside this module is a lint violation,
and a helper read of an unregistered name raises here at runtime.

Why a registry: before PR 6, `"0"` / empty-string / unset truthiness was
decided ad hoc at each read site (`env != "0"`, `== "1"`,
`bool(os.environ.get(...))` — three different rules, one of which made
`KUBERNETRIKS_FAST_TESTS=0` truthy). The registry gives every flag ONE
parser, one default, and one greppable declaration.

Truthiness rule (flag_bool / flag_tristate): unset -> default (or None for
tristate); `"0"`, `""`, `"false"`, `"no"`, `"off"` (case-insensitive) ->
False; anything else -> True.
"""

from __future__ import annotations

import os
from typing import Dict, NamedTuple, Optional


class Flag(NamedTuple):
    name: str
    type: str  # "bool" | "tristate" | "str" | "int"
    default: object
    doc: str


_FLAGS = [
    Flag(
        "KTPU_DONATE",
        "tristate",
        None,
        "Buffer donation for the steady-state dispatch loop (donated jit "
        "entries consume the input state in place). Unset: on for "
        "accelerator backends, off on CPU hosts.",
    ),
    Flag(
        "KTPU_FUSED_SLIDE",
        "tristate",
        None,
        "Fused chunk+slide megastep: the last ladder chunk of a slide span "
        "also computes and applies the window slide on device. Unset: on "
        "for accelerator backends, off on CPU hosts.",
    ),
    Flag(
        "KTPU_SUPERSPAN",
        "tristate",
        None,
        "Superspan executor: one jitted while_loop retires up to K "
        "consecutive slide-spans per dispatch. Unset: on for accelerator "
        "backends, off on CPU hosts.",
    ),
    Flag(
        "KTPU_STREAM",
        "tristate",
        None,
        "Streaming trace-ingestion pipeline (batched/stream.py): a feeder "
        "thread compiles trace segments into a bounded ring of K "
        "device-resident staging slabs, running ahead of the superspan "
        "executor so stage-exhaustion exits find the next slab already "
        "uploaded and the whole-trace device slide payload is never "
        "materialized. Rides the superspan executor (inactive when "
        "KTPU_SUPERSPAN is off). Unset: on for accelerator backends, off "
        "on CPU hosts — the same platform default as KTPU_SUPERSPAN.",
    ),
    Flag(
        "KTPU_STREAM_DEPTH",
        "int",
        3,
        "Ring depth K of the streaming feeder: at most K staging slabs "
        "live on device at once (the memory bound). K = 1 degenerates to "
        "synchronous-but-off-thread staging and stays exact.",
    ),
    Flag(
        "KTPU_STREAM_SEGMENT",
        "int",
        None,
        "Staging-segment width (payload columns) of the streaming "
        "feeder's slabs. Unset: the superspan stage default (4x the pod "
        "window, clamped to [W + W/2, whole payload]). Width is a jit "
        "static — changing it recompiles the superspan program.",
    ),
    Flag(
        "KTPU_LANE_MAJOR",
        "tristate",
        None,
        "Lane-major hot node state: inside every window program the hot "
        "(C, N) node leaves (alive, caps, allocatables, crash payload) are "
        "carried TRANSPOSED (N, C) — the layout the Pallas kernels consume "
        "— so the event/free/cycle kernel wrappers skip their per-boundary "
        "transposes and the XLA glue runs elementwise on the kernel "
        "layout. Bit-identical to the row-major path (float metric sums "
        "within the documented docs/PARITY.md tolerance). Unset: on for "
        "accelerator backends, off on CPU hosts (where XLA pays the "
        "transposes anyway and the row-major path avoids the extra "
        "program variants). Unsupported (ignored) under a device mesh.",
    ),
    Flag(
        "KTPU_WINDOW_RAZOR",
        "tristate",
        None,
        "Window-cost razor: gate the per-window event-resolution soup "
        "(event application, pending-effect merge, finish/interrupt "
        "resolution, free/reschedule bookkeeping) behind a cheap due-ness "
        "predicate, so empty and near-empty windows in dense traces skip "
        "the masked elementwise passes entirely. Bit-exact: the skip "
        "branch fires only when the soup is provably the identity. Unset: "
        "on for accelerator backends; off on CPU hosts, where the cond "
        "adds compile time to every window program and the measured win "
        "is marginal (BENCH_r07 A/B). 0/1 force for A/B measurement.",
    ),
    Flag(
        "KTPU_CA_DESCATTER",
        "bool",
        True,
        "CA scale-down de-scatter (round 3 of the campaign): the "
        "finish-visibility allocatable correction and the node-grouping "
        "sort share ONE combined 2-key (C, P) sort and one set of "
        "segment-boundary reductions instead of two sorts + four "
        "(C, P, N) rank-count passes. Integer segment sums — bit-exact. "
        "0 selects the r5 two-sort path for A/B measurement.",
    ),
    Flag(
        "KTPU_RECLAIM",
        "tristate",
        None,
        "CA slot reclaim (batched/autoscale.py ca_reclaim_pass): a "
        "periodic in-trace compaction returns fully-retired CA reserve "
        "slots to their group, so ca_cursor tracks LIVE occupancy and "
        "sustained churn never exhausts the reserve (the ROADMAP #2 "
        "endurance blocker). Trajectories stay scalar-exact: allocations "
        "carry the scalar's total_allocated naming index and every "
        "name-ordered walk derives its order from it. 0 compiles the "
        "pre-reclaim programs (the A/B bit-identity gate; the loud "
        "reserve bound is then the only backstop). Unset: on for "
        "accelerator backends, off on CPU hosts — tests and endurance "
        "runs opt in explicitly. Forced off (warning) when the trace's "
        "node-name classes interleave; an explicit 1 raises there.",
    ),
    Flag(
        "KTPU_RECLAIM_PERIOD",
        "int",
        1,
        "Reclaim compaction cadence in windows: 1 (default) compacts in "
        "any window with a retired slot (a scale-up can then never "
        "starve while reclaimable slots exist); larger values batch the "
        "compaction's (C, P) retirement-safety sweep to every Nth "
        "window, trading a transiently tighter reserve for less work.",
    ),
    Flag(
        "KTPU_ALIGN_PODS",
        "bool",
        True,
        "128-align the pod axis of full-resident runs so Pallas block pads "
        "are no-ops.",
    ),
    Flag(
        "KTPU_MEGAKERNEL",
        "bool",
        True,
        "Fused selection+cycle+commit Pallas megakernel on the dense path "
        "(0 selects the two-kernel path for A/B measurement). Read at "
        "engine build time and threaded as a jit-static.",
    ),
    Flag(
        "KTPU_DEBUG_FINITE",
        "bool",
        False,
        "Guard mode: host-side NaN/inf sweep over every float state leaf "
        "after each dispatched chunk, naming the offending field. Keeps "
        "the ladder path (per-chunk localization).",
    ),
    Flag(
        "KTPU_SANITIZE",
        "bool",
        False,
        "Runtime sanitizer: the engine's steady-state dispatch region runs "
        "under jax.transfer_guard('disallow_explicit') for device-to-host "
        "transfers (waived syncs carry explicit allow scopes), donated "
        "inputs are force-deleted after donated calls so read-after-donate "
        "crashes even on CPU (where XLA donation is a no-op), and the "
        "KTPU_DEBUG_FINITE state sweep runs at every dispatch boundary.",
    ),
    Flag(
        "KTPU_PROFILE",
        "str",
        None,
        "Named scheduler profile for batched engines that were not handed "
        "an explicit profile (bench/CLI selection): a key of "
        "core.scheduler.kube_scheduler.NAMED_PROFILE_SPECS ('default', "
        "'best_fit', 'balanced_packing'). Compiled into the scan and "
        "Pallas kernel paths at engine build (batched/pipeline.py); an "
        "unknown name or un-lowerable plugin raises at construction "
        "instead of silently running the default pipeline. Unset: the "
        "config's scheduler_profile, else the reference default.",
    ),
    Flag(
        "KTPU_EXPLAIN_RECOMPILES",
        "tristate",
        None,
        "Recompile sentinel (kubernetriks_tpu/recompile.py): a "
        "jax.log_compiles-based monitor that raises RecompileError "
        "naming the jit entry on any post-warm-up XLA compilation — the "
        "runtime cross-check of the fleet's compile-once guarantee (the "
        "scenariotrace lint pass is the static half). Unset: armed only "
        "by the bench.py --sweep/--endurance in-bench asserts; 1: "
        "ScenarioFleet guards every post-warm-up wave; 0: forced off "
        "everywhere, including the benches.",
    ),
    Flag(
        "KTPU_TRACE",
        "bool",
        False,
        "Flight recorder: host-side span tracer over every engine dispatch "
        "phase plus the device-side per-window metrics ring carried in "
        "ClusterBatchState. Read out via engine.telemetry_report() / "
        "write_chrome_trace(); bench.py --trace embeds the summary in the "
        "BENCH JSON. Off by default (telemetry-on is bit-identical and "
        "gated <3% overhead, but the ring costs device memory).",
    ),
    Flag(
        "KTPU_TRACE_PATH",
        "str",
        None,
        "Output path stem for Chrome trace-event JSON written by "
        "bench.py --trace (Perfetto-loadable). Unset: ktpu_trace under the "
        "working directory.",
    ),
    Flag(
        "KTPU_WATCHDOG",
        "tristate",
        None,
        "Saturation watchdog (telemetry/observatory.py): at every "
        "telemetry-ring drain, fit the reserve-occupancy trajectories "
        "(CA node-slot reserve, HPA pod-reserve, pod-window headroom) and "
        "emit SaturationWarning with an estimated time-to-exhaustion "
        "BEFORE the loud reserve bound fires; also flags feeder "
        "starvation and sync-budget violations. Unset: armed exactly when "
        "the flight recorder is (KTPU_TRACE / telemetry=True) — it reads "
        "the ring's occupancy columns, so it rides telemetry; an explicit "
        "1 with telemetry off raises at engine build instead of silently "
        "watching nothing.",
    ),
    Flag(
        "KTPU_METRICS_PATH",
        "str",
        None,
        "Output path stem for the capacity observatory's time-series "
        "export (telemetry/export.py): bench.py --trace appends drain "
        "records to <stem>_<label>.jsonl (bounded, rotating) and writes "
        "the final report as <stem>_<label>.prom (Prometheus textfile). "
        "Unset: ktpu_metrics under the working directory.",
    ),
    Flag(
        "KTPU_SWEEP_PATH",
        "str",
        None,
        "Output path stem for bench.py --sweep's JSON record (scenario "
        "fleet vs per-engine baseline, wave timings, recompile/cross-talk "
        "verdicts): the sweep writes <stem>.json (CI uploads it next to "
        "the trace artifacts). Unset: ktpu_sweep under the working "
        "directory.",
    ),
    Flag(
        "KTPU_SWEEP_LANES",
        "int",
        None,
        "Cluster-lane count C of bench.py --sweep's resident scenario "
        "fleet (batched/fleet.py): N scenarios pack into ceil(N/C) waves "
        "over ONE compiled engine. Unset: the sweep shape default (16; "
        "4 on --smoke).",
    ),
    Flag(
        "KTPU_SWEEP_BASELINE",
        "int",
        None,
        "How many independent per-scenario engines the --sweep baseline "
        "actually builds and times (the rest of the N-engine baseline is "
        "extrapolated from their mean and disclosed as such in the JSON). "
        "Unset: 3.",
    ),
    Flag(
        "KTPU_LANE_SPAN",
        "int",
        None,
        "Pump span (windows per round) of the lane-asynchronous fleet's "
        "continuous submit/poll engine (batched/fleet.py pump()): every "
        "round steps ALL lanes this many global windows through one "
        "compiled fixed-span program, then re-seeds the lanes whose "
        "per-lane clock finished. Smaller spans cut completion latency "
        "and idle-lane waste at more dispatch overhead. Unset: 8.",
    ),
    Flag(
        "KTPU_HOST_CHAOS",
        "str",
        None,
        "Deterministic HOST-fault injection for the serving fleet "
        "(batched/faults.py HostChaos): counter-seeded threefry draws "
        "inject dispatch exceptions (victim lane cycles round-robin), "
        "stream-feeder producer kills, and slow-lane stalls, so the "
        "fault-domain machinery (typed QueryError results, lane_reset "
        "crash recovery, quarantine, feeder supervisor) is provable in "
        "CI. '1' selects the documented defaults "
        "(seed=7,dispatch=0.04,feeder=0.05,stall=0.03,stall_ms=2.0); a "
        "'k=v,...' spec overrides them. Unset: injection OFF — the fleet "
        "runs the exact pre-chaos code path (per-query bit-identity and "
        "dispatch_stats equality, gated in tests and bench).",
    ),
    Flag(
        "KTPU_FLEET_QUEUE",
        "int",
        None,
        "Bounded admission queue depth for ScenarioFleet.submit(): at "
        "most this many queries may be QUEUED (in-flight lanes excluded). "
        "A full queue applies the KTPU_FLEET_QUEUE_POLICY backpressure. "
        "Unset: unbounded (the pre-fault-domain behavior).",
    ),
    Flag(
        "KTPU_FLEET_QUEUE_POLICY",
        "str",
        "reject",
        "Backpressure policy when the bounded admission queue is full: "
        "'reject' streams a RejectedError (with a retry_after_s hint "
        "derived from the observed service rate) through poll() for the "
        "refused query; 'block' makes submit() pump the fleet inline "
        "until a queue slot frees. Ignored while KTPU_FLEET_QUEUE is "
        "unset.",
    ),
    Flag(
        "KTPU_SLO_MS",
        "int",
        None,
        "Latency-SLO target in milliseconds (submit-to-drain wall) for "
        "lane-async fleet queries: arms the capacity observatory's SLO "
        "burn-rate verdicts (telemetry/observatory.py) — fast/slow "
        "error-budget burn alerting with hysteresis, windowed by "
        "KTPU_SLO_BURN_WINDOW. Unset: SLO verdicts disarmed.",
    ),
    Flag(
        "KTPU_SLO_BURN_WINDOW",
        "int",
        60,
        "Fast burn-rate window (wall seconds) for the SLO verdict; the "
        "slow-burn window is 12x this. Default: 60.",
    ),
    Flag(
        "KUBERNETRIKS_PALLAS",
        "tristate",
        None,
        "Force the Pallas scheduling-cycle kernels on (1) or off (0). "
        "Unset: auto — on for TPU backends whose blocks fit VMEM.",
    ),
    Flag(
        "KUBERNETRIKS_LOG",
        "str",
        "INFO",
        "CLI logging level (DEBUG/INFO/WARNING/ERROR).",
    ),
    Flag(
        "KUBERNETRIKS_FAST_TESTS",
        "bool",
        False,
        "DEPRECATED no-op since PR 6: the fast scales it used to opt into "
        "are the tier-1 default, and the reference-scale runs live behind "
        "`-m slow`. Registered so existing scripts that set it keep "
        "passing the env-flag lint; nothing reads it.",
    ),
    Flag(
        "KUBERNETRIKS_ALIBABA_DIR",
        "str",
        None,
        "Directory holding the real Alibaba v2017 trace CSVs; enables the "
        "real-trace feeder tests when set.",
    ),
    Flag(
        "KTPU_TUNE",
        "bool",
        False,
        "Run the measurement-driven statics autotuner (tune/) from "
        "bench.py without the --tune CLI flag: sweep the registered "
        "performance knobs with the bench protocol and the observatory "
        "objective, then persist the winning per-hardware profile under "
        "artifacts/tuned/<backend>_<C>x<N>.json. Equivalent to "
        "`bench.py --tune`.",
    ),
    Flag(
        "KTPU_TUNED_PROFILE",
        "str",
        None,
        "Tuned-statics profile for engine builds (tune/profile.py): a "
        "path to a profile JSON (strict — missing file or "
        "backend/geometry mismatch raises, naming the field), or "
        "1/auto/true/on to auto-resolve artifacts/tuned/ then the "
        "bundled kubernetriks_tpu/tune/profiles/ directory by the "
        "build's backend + lane count (no match: hand-picked statics, "
        "quietly). Per knob the profile ranks BELOW the knob's own env "
        "flag and explicit build kwargs, ABOVE the platform default. "
        "Unset: no profile is ever consulted — builds stay byte-for-byte "
        "the pre-tuner behavior.",
    ),
    Flag(
        "KTPU_TUNE_BUDGET",
        "int",
        None,
        "Cap on NEW measurements per autotuner run (resume-cache hits "
        "are free): an exhausted budget stops the sweep and persists a "
        "partial profile marked complete=false, which a rerun resumes "
        "from. Unset: unbounded (the full staged coordinate descent).",
    ),
]

REGISTRY: Dict[str, Flag] = {f.name: f for f in _FLAGS}

_FALSY = frozenset({"0", "", "false", "no", "off"})


def _lookup(name: str, expected: str) -> Flag:
    flag = REGISTRY.get(name)
    if flag is None:
        raise KeyError(
            f"environment flag {name!r} is not registered in "
            "kubernetriks_tpu.flags — declare it (name, type, default, doc) "
            "before reading it"
        )
    if flag.type != expected:
        raise TypeError(
            f"environment flag {name!r} is registered as {flag.type!r}, "
            f"read as {expected!r}"
        )
    return flag


def parse_bool(raw: str) -> bool:
    """THE truthiness rule for flag strings (see module docstring)."""
    return raw.strip().lower() not in _FALSY


def flag_set(name: str) -> bool:
    """Whether the flag is present in the environment at all — for the
    few flags with a concrete (non-None) registered default that a tuned
    profile may override: the profile ranks below an explicitly SET flag
    but above the registry default, so "set vs unset" must be observable
    (flag_bool/flag_int collapse the two)."""
    flag = REGISTRY.get(name)
    if flag is None:
        raise KeyError(
            f"environment flag {name!r} is not registered in "
            "kubernetriks_tpu.flags — declare it (name, type, default, doc) "
            "before reading it"
        )
    return name in os.environ


def flag_bool(name: str) -> bool:
    """Boolean flag: unset -> registered default; else parse_bool."""
    flag = _lookup(name, "bool")
    raw = os.environ.get(name)
    if raw is None:
        return bool(flag.default)
    return parse_bool(raw)


def flag_tristate(name: str) -> Optional[bool]:
    """Tri-state flag: None when unset (caller picks a platform default),
    else parse_bool."""
    _lookup(name, "tristate")
    raw = os.environ.get(name)
    if raw is None:
        return None
    return parse_bool(raw)


def flag_str(name: str) -> Optional[str]:
    """String flag: unset -> registered default (may be None)."""
    flag = _lookup(name, "str")
    raw = os.environ.get(name)
    if raw is None:
        return flag.default  # type: ignore[return-value]
    return raw


def flag_int(name: str) -> Optional[int]:
    """Integer flag: unset or empty -> registered default (may be None);
    anything else must parse as a base-10 integer (a typo'd value raises
    here, at the registry, instead of silently selecting a default)."""
    flag = _lookup(name, "int")
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return flag.default  # type: ignore[return-value]
    try:
        return int(raw.strip(), 10)
    except ValueError as exc:
        raise ValueError(
            f"environment flag {name!r} must be an integer, got {raw!r}"
        ) from exc
