"""Scheduler plugin registry with Fit and LeastAllocatedResources built-ins
(reference: src/core/scheduler/plugin.rs), extended with the packing-side
scorers the batched device pipeline also lowers (MostAllocatedResources,
BalancedResourceAllocation).

The plugin NAME constants below are the shared vocabulary between this
scalar registry and the device-plugin registry in
kubernetriks_tpu/batched/pipeline.py: a profile referencing these names runs
on both paths with one definition of the semantics (the batched registry
validates against them at engine construction and raises loudly on a name it
cannot lower)."""

from __future__ import annotations

from typing import Dict, List, Union

from kubernetriks_tpu.core.types import Node, Pod

# Shared plugin-name constants (scalar registry keys == device registry keys).
FIT = "Fit"
LEAST_ALLOCATED = "LeastAllocatedResources"
MOST_ALLOCATED = "MostAllocatedResources"
BALANCED = "BalancedResourceAllocation"


class FilterPlugin:
    def filter(self, pod: Pod, nodes: List[Node]) -> List[Node]:
        raise NotImplementedError


class ScorePlugin:
    def score(self, pod: Pod, node: Node) -> float:
        raise NotImplementedError


class Fit(FilterPlugin):
    """Keep nodes whose allocatable covers the pod's requests
    (reference: src/core/scheduler/plugin.rs:33-45)."""

    def filter(self, pod: Pod, nodes: List[Node]) -> List[Node]:
        requests = pod.spec.resources.requests
        return [
            node
            for node in nodes
            if requests.cpu <= node.status.allocatable.cpu
            and requests.ram <= node.status.allocatable.ram
        ]


class LeastAllocatedResources(ScorePlugin):
    """Mean of the percentage of cpu+ram left after placement, relative to the
    node's current allocatable (reference: src/core/scheduler/plugin.rs:47-63)."""

    def score(self, pod: Pod, node: Node) -> float:
        requests = pod.spec.resources.requests
        allocatable = node.status.allocatable
        # Zero allocatable yields NaN, matching the reference's f64 division
        # (plugin.rs:54-62); NaN never displaces a finite score in the `>=`
        # argmax (the degenerate NaN-seed case is documented in DESIGN §9.4).
        cpu_score = (
            (allocatable.cpu - requests.cpu) * 100.0 / allocatable.cpu
            if allocatable.cpu
            else float("nan")
        )
        ram_score = (
            (allocatable.ram - requests.ram) * 100.0 / allocatable.ram
            if allocatable.ram
            else float("nan")
        )
        return (cpu_score + ram_score) / 2.0


class MostAllocatedResources(ScorePlugin):
    """Best-fit packing: the exact negation of LeastAllocatedResources per
    resource — mean percentage of the node's current allocatable the pod
    would CONSUME, so the tightest-fitting node scores highest. Zero
    allocatable keeps the NaN convention above (the device pipeline lowers
    it to -inf; neither ever wins the argmax)."""

    def score(self, pod: Pod, node: Node) -> float:
        requests = pod.spec.resources.requests
        allocatable = node.status.allocatable
        cpu_score = (
            (requests.cpu - allocatable.cpu) * 100.0 / allocatable.cpu
            if allocatable.cpu
            else float("nan")
        )
        ram_score = (
            (requests.ram - allocatable.ram) * 100.0 / allocatable.ram
            if allocatable.ram
            else float("nan")
        )
        return (cpu_score + ram_score) / 2.0


class BalancedResourceAllocation(ScorePlugin):
    """100 minus the percentage-point imbalance between the cpu and ram
    fractions of the node's current allocatable the pod would consume —
    favors placements that drain both resources evenly (the shape of
    upstream Kubernetes' NodeResourcesBalancedAllocation, stated against
    allocatable like the two scorers above)."""

    def score(self, pod: Pod, node: Node) -> float:
        requests = pod.spec.resources.requests
        allocatable = node.status.allocatable
        if not allocatable.cpu or not allocatable.ram:
            return float("nan")
        cpu_frac = requests.cpu / allocatable.cpu
        ram_frac = requests.ram / allocatable.ram
        return 100.0 - abs(cpu_frac - ram_frac) * 100.0


PLUGIN_REGISTRY: Dict[str, Union[FilterPlugin, ScorePlugin]] = {
    FIT: Fit(),
    LEAST_ALLOCATED: LeastAllocatedResources(),
    MOST_ALLOCATED: MostAllocatedResources(),
    BALANCED: BalancedResourceAllocation(),
}


def register_plugin(name: str, plugin: Union[FilterPlugin, ScorePlugin]) -> None:
    """Extension point for custom plugins (the reference's registry is a static
    map; here plugins may be registered at runtime). A runtime-registered
    plugin runs on the SCALAR path only — the batched engine refuses profiles
    it cannot lower (batched/pipeline.py) instead of silently substituting
    the default."""
    PLUGIN_REGISTRY[name] = plugin
