"""Scheduler plugin registry with Fit and LeastAllocatedResources built-ins
(reference: src/core/scheduler/plugin.rs)."""

from __future__ import annotations

from typing import Dict, List, Union

from kubernetriks_tpu.core.types import Node, Pod


class FilterPlugin:
    def filter(self, pod: Pod, nodes: List[Node]) -> List[Node]:
        raise NotImplementedError


class ScorePlugin:
    def score(self, pod: Pod, node: Node) -> float:
        raise NotImplementedError


class Fit(FilterPlugin):
    """Keep nodes whose allocatable covers the pod's requests
    (reference: src/core/scheduler/plugin.rs:33-45)."""

    def filter(self, pod: Pod, nodes: List[Node]) -> List[Node]:
        requests = pod.spec.resources.requests
        return [
            node
            for node in nodes
            if requests.cpu <= node.status.allocatable.cpu
            and requests.ram <= node.status.allocatable.ram
        ]


class LeastAllocatedResources(ScorePlugin):
    """Mean of the percentage of cpu+ram left after placement, relative to the
    node's current allocatable (reference: src/core/scheduler/plugin.rs:47-63)."""

    def score(self, pod: Pod, node: Node) -> float:
        requests = pod.spec.resources.requests
        allocatable = node.status.allocatable
        # Zero allocatable yields NaN, matching the reference's f64 division
        # (plugin.rs:54-62); NaN never wins the `>=` argmax.
        cpu_score = (
            (allocatable.cpu - requests.cpu) * 100.0 / allocatable.cpu
            if allocatable.cpu
            else float("nan")
        )
        ram_score = (
            (allocatable.ram - requests.ram) * 100.0 / allocatable.ram
            if allocatable.ram
            else float("nan")
        )
        return (cpu_score + ram_score) / 2.0


PLUGIN_REGISTRY: Dict[str, Union[FilterPlugin, ScorePlugin]] = {
    "Fit": Fit(),
    "LeastAllocatedResources": LeastAllocatedResources(),
}


def register_plugin(name: str, plugin: Union[FilterPlugin, ScorePlugin]) -> None:
    """Extension point for custom plugins (the reference's registry is a static
    map; here plugins may be registered at runtime)."""
    PLUGIN_REGISTRY[name] = plugin
