"""Scheduler component: queueing machinery + periodic scheduling cycles.

Mirrors the reference's Scheduler (reference: src/core/scheduler/scheduler.rs):
an active min-heap queue and an unschedulable map, a drain-the-queue scheduling
cycle with simulated per-pod algorithm latency, requeue/reschedule on node
removal / pod finish / pod removal, and conditional vs flush-all move policies.
"""

from __future__ import annotations

from typing import Callable, Dict, Set, TYPE_CHECKING

from kubernetriks_tpu.core.events import (
    AddNodeToCache,
    AssignPodToNodeRequest,
    FlushUnschedulableQueueLeftover,
    PodFinishedRunning,
    PodNotScheduled,
    PodScheduleRequest,
    RemoveNodeFromCache,
    RemovePodFromCache,
    RequeuePodAfterBackoff,
    RunSchedulingCycle,
)
from kubernetriks_tpu.core.scheduler.interface import (
    PodSchedulingAlgorithm,
    SchedulingFailure,
)
from kubernetriks_tpu.core.scheduler.model import (
    ConstantTimePerNodeModel,
    PodSchedulingTimeModel,
)
from kubernetriks_tpu.core.scheduler.queue import (
    ActiveQueue,
    DEFAULT_POD_MAX_IN_UNSCHEDULABLE_PODS_DURATION,
    POD_FLUSH_INTERVAL,
    QueuedPodInfo,
    UnschedulablePodKey,
    UnschedulableQueue,
)
from kubernetriks_tpu.core.types import Node, ObjectsInfo, Pod, RuntimeResources
from kubernetriks_tpu.sim.kernel import EventHandler, SimulationContext

if TYPE_CHECKING:
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.metrics.collector import MetricsCollector


class Scheduler(EventHandler):
    def __init__(
        self,
        api_server: int,
        scheduler_algorithm: PodSchedulingAlgorithm,
        ctx: SimulationContext,
        config: "SimulationConfig",
        metrics_collector: "MetricsCollector",
    ) -> None:
        self.api_server = api_server
        self.objects_cache = ObjectsInfo()
        # node name -> pod names assigned by this scheduler
        self.assignments: Dict[str, Set[str]] = {}
        self.scheduler_algorithm = scheduler_algorithm
        self.pod_scheduling_time_model: PodSchedulingTimeModel = (
            ConstantTimePerNodeModel()
        )
        self.action_queue = ActiveQueue()
        self.unschedulable_pods = UnschedulableQueue()
        self.ctx = ctx
        self.config = config
        self.metrics_collector = metrics_collector
        # Chaos engine: pod fault oracle (backoff/limit reads); installed by
        # the simulator when fault injection is on.
        self.fault_oracle = None

    def start(self) -> None:
        """Arm both self-tick cycles (reference: src/core/scheduler/scheduler.rs:78-81)."""
        self.ctx.emit_self_now(RunSchedulingCycle())
        self.ctx.emit_self_now(FlushUnschedulableQueueLeftover())

    # --- cache API ----------------------------------------------------------

    def add_node(self, node: Node) -> None:
        self.objects_cache.nodes[node.metadata.name] = node

    def add_pod(self, pod: Pod) -> None:
        self.objects_cache.pods[pod.metadata.name] = pod

    def get_node(self, node_name: str) -> Node:
        return self.objects_cache.nodes[node_name]

    def get_pod(self, pod_name: str) -> Pod:
        return self.objects_cache.pods[pod_name]

    def node_count(self) -> int:
        return len(self.objects_cache.nodes)

    def pod_count(self) -> int:
        return len(self.objects_cache.pods)

    def set_scheduler_algorithm(self, algorithm: PodSchedulingAlgorithm) -> None:
        self.scheduler_algorithm = algorithm

    # --- resource bookkeeping ----------------------------------------------

    def reserve_node_resources(self, pod_name: str, assigned_node: str) -> None:
        pod = self.objects_cache.pods[pod_name]
        node = self.objects_cache.nodes[assigned_node]
        node.status.allocatable.cpu -= pod.spec.resources.requests.cpu
        node.status.allocatable.ram -= pod.spec.resources.requests.ram

    def assign_node_to_pod(self, pod_name: str, node_name: str) -> None:
        self.assignments.setdefault(node_name, set()).add(pod_name)
        self.objects_cache.pods[pod_name].status.assigned_node = node_name

    def release_node_resources(self, pod: Pod) -> None:
        node = self.objects_cache.nodes[pod.status.assigned_node]
        node.status.allocatable.cpu += pod.spec.resources.requests.cpu
        node.status.allocatable.ram += pod.spec.resources.requests.ram

    def schedule_one(self, pod: Pod) -> str:
        return self.scheduler_algorithm.schedule_one(pod, self.objects_cache.nodes)

    # --- queue movement -----------------------------------------------------

    def _move_pods_to_active_queue(self, keys) -> None:
        """reference: src/core/scheduler/scheduler.rs:174-186."""
        for key in keys:
            if key.pod_name not in self.objects_cache.pods:
                continue
            info = self.unschedulable_pods.remove(key)
            info.attempts += 1
            self.action_queue.push(info)

    def flush_unschedulable_pods_leftover(self, event_time: float) -> None:
        """Move pods stuck in unschedulable for >300 s; re-arm the 30 s cycle
        (reference: src/core/scheduler/scheduler.rs:188-203)."""
        to_move = [
            key
            for key, info in self.unschedulable_pods.sorted_items()
            if event_time - info.timestamp > DEFAULT_POD_MAX_IN_UNSCHEDULABLE_PODS_DURATION
        ]
        self._move_pods_to_active_queue(to_move)
        self.ctx.emit_self(FlushUnschedulableQueueLeftover(), POD_FLUSH_INTERVAL)

    def move_to_active_queue_if(
        self, check: Callable[[RuntimeResources], bool]
    ) -> None:
        """Move pods whose requests satisfy `check` (which may mutate captured
        state to account resources as it accepts pods)
        (reference: src/core/scheduler/scheduler.rs:205-234)."""
        to_move = [
            key
            for key, info in self.unschedulable_pods.sorted_items()
            if check(self.objects_cache.pods[info.pod_name].spec.resources.requests)
        ]
        self._move_pods_to_active_queue(to_move)

    def move_all_to_active_queue(self) -> None:
        self._move_pods_to_active_queue(self.unschedulable_pods.sorted_keys())

    # --- scheduling cycle (hot loop) ----------------------------------------

    def run_scheduling_cycle(self, cycle_event_time: float) -> None:
        """Drain the active queue, assigning or parking each pod; accumulated
        simulated algorithm latency shifts each assignment's effect time
        (reference: src/core/scheduler/scheduler.rs:246-333)."""
        cycle_sim_duration = 0.0
        metrics = self.metrics_collector
        metrics.gauge_metrics.pods_in_scheduling_queues = len(self.action_queue) + len(
            self.unschedulable_pods
        )

        while True:
            next_pod = self.action_queue.pop()
            if next_pod is None:
                break
            # Pod may have been removed via RemovePodFromCache while queued.
            if next_pod.pod_name not in self.objects_cache.pods:
                continue

            pod_queue_time = (
                cycle_event_time - next_pod.initial_attempt_timestamp + cycle_sim_duration
            )
            pod = self.objects_cache.pods[next_pod.pod_name]
            pod_schedule_time = self.pod_scheduling_time_model.simulate_time(
                pod, self.objects_cache.nodes
            )
            cycle_sim_duration += pod_schedule_time

            try:
                assigned_node = self.schedule_one(pod)
            except SchedulingFailure:
                next_pod.timestamp = cycle_event_time + cycle_sim_duration
                self.unschedulable_pods.insert(
                    UnschedulablePodKey(
                        pod_name=next_pod.pod_name,
                        insert_timestamp=next_pod.timestamp,
                    ),
                    next_pod,
                )
                self.ctx.emit(
                    PodNotScheduled(
                        not_scheduled_time=cycle_event_time + cycle_sim_duration,
                        pod_name=pod.metadata.name,
                    ),
                    self.api_server,
                    self.config.sched_to_as_network_delay,
                )
                continue

            self.reserve_node_resources(next_pod.pod_name, assigned_node)
            self.assign_node_to_pod(next_pod.pod_name, assigned_node)
            self.ctx.emit(
                AssignPodToNodeRequest(
                    assign_time=cycle_event_time + cycle_sim_duration,
                    pod_name=next_pod.pod_name,
                    node_name=assigned_node,
                ),
                self.api_server,
                cycle_sim_duration + self.config.sched_to_as_network_delay,
            )
            metrics.accumulated_metrics.increment_pod_scheduling_algorithm_latency(
                pod_schedule_time
            )
            metrics.accumulated_metrics.increment_pod_queue_time(pod_queue_time)

        next_cycle_delay = max(cycle_sim_duration, self.config.scheduling_cycle_interval)
        self.ctx.emit_self(RunSchedulingCycle(), next_cycle_delay)

    # --- rescheduling -------------------------------------------------------

    def reschedule_pod(self, pod_name: str, event_time: float) -> None:
        self.objects_cache.pods[pod_name].status.assigned_node = ""
        self.action_queue.push(
            QueuedPodInfo(
                timestamp=event_time,
                attempts=1,
                initial_attempt_timestamp=event_time,
                pod_name=pod_name,
            )
        )

    def reschedule_unfinished_pods(self, node_name: str, event_time: float) -> int:
        """All pods of a dead node go back to the active queue in sorted-name
        order (reference: src/core/scheduler/scheduler.rs:336-364). Returns
        the reschedule count (the chaos engine's interruption metric)."""
        unfinished = self.assignments.pop(node_name, None)
        if not unfinished:
            return 0
        for pod_name in sorted(unfinished):
            self.reschedule_pod(pod_name, event_time)
        return len(unfinished)

    def _move_to_active_due_to_pod_freed_resources(
        self, freed: RuntimeResources
    ) -> None:
        """Greedy first-fit against the freed budget, decrementing it per
        accepted pod (reference: src/core/scheduler/scheduler.rs:366-380)."""
        remaining = freed.copy()

        def check(requests: RuntimeResources) -> bool:
            if requests.cpu <= remaining.cpu and requests.ram <= remaining.ram:
                remaining.cpu -= requests.cpu
                remaining.ram -= requests.ram
                return True
            return False

        self.move_to_active_queue_if(check)

    # --- event handlers -----------------------------------------------------

    def on_run_scheduling_cycle(self, data: RunSchedulingCycle, time: float) -> None:
        self.run_scheduling_cycle(time)

    def on_flush_unschedulable_queue_leftover(
        self, data: FlushUnschedulableQueueLeftover, time: float
    ) -> None:
        self.flush_unschedulable_pods_leftover(time)

    def on_add_node_to_cache(self, data: AddNodeToCache, time: float) -> None:
        """reference: src/core/scheduler/scheduler.rs:391-410."""
        node = data.node
        allocatable = node.status.allocatable.copy()
        self.add_node(node)

        if self.config.enable_unscheduled_pods_conditional_move:

            def check(requests: RuntimeResources) -> bool:
                if requests.cpu <= allocatable.cpu and requests.ram <= allocatable.ram:
                    allocatable.cpu -= requests.cpu
                    allocatable.ram -= requests.ram
                    return False
                return True

            self.move_to_active_queue_if(check)
        else:
            self.move_all_to_active_queue()

    def on_pod_schedule_request(self, data: PodScheduleRequest, time: float) -> None:
        pod_name = data.pod.metadata.name
        self.add_pod(data.pod)
        self.action_queue.push(
            QueuedPodInfo(
                timestamp=time,
                attempts=1,
                initial_attempt_timestamp=time,
                pod_name=pod_name,
            )
        )

    def on_pod_finished_running(self, data: PodFinishedRunning, time: float) -> None:
        from kubernetriks_tpu.core.types import PodConditionType

        if data.finish_result == PodConditionType.POD_FAILED:
            self._on_pod_failed(data, time)
            return
        pod = self.objects_cache.pods.pop(data.pod_name)
        self.assignments[data.node_name].discard(data.pod_name)
        self.release_node_resources(pod)
        if self.config.enable_unscheduled_pods_conditional_move:
            self._move_to_active_due_to_pod_freed_resources(
                pod.spec.resources.requests.copy()
            )
        else:
            self.move_all_to_active_queue()

    def _on_pod_failed(self, data: PodFinishedRunning, time: float) -> None:
        """Chaos-engine attempt failure: free the node's resources, then
        either requeue with CrashLoopBackOff (new active-queue entry at
        fail_time + min(base * 2^k, cap), fresh initial-attempt timestamp —
        mirroring the batched retry disposition) or drop the pod as
        permanently failed. Both outcomes wake the unschedulable queue like
        a finish — resources were freed either way."""
        pod = self.objects_cache.pods.get(data.pod_name)
        if pod is None:
            return  # removed while the failure was in flight
        self.assignments.get(data.node_name, set()).discard(data.pod_name)
        if data.node_name in self.objects_cache.nodes:
            self.release_node_resources(pod)
        if self.fault_oracle.is_permanently_failed(data.pod_name):
            self.objects_cache.pods.pop(data.pod_name)
        else:
            pod.status.assigned_node = ""
            requeue_ts = data.finish_time + self.fault_oracle.backoff_after_failure(
                data.pod_name
            )
            # Deliver at backoff expiry: each cycle drains the whole active
            # queue, so pushing a future-timestamped entry now would defeat
            # the backoff (the batched path gates on queue_ts < cycle time).
            self.ctx.emit_self(
                RequeuePodAfterBackoff(
                    pod_name=data.pod_name, requeue_ts=requeue_ts
                ),
                max(requeue_ts - time, 0.0),
            )
        if self.config.enable_unscheduled_pods_conditional_move:
            self._move_to_active_due_to_pod_freed_resources(
                pod.spec.resources.requests.copy()
            )
        else:
            self.move_all_to_active_queue()

    def on_requeue_pod_after_backoff(
        self, data: RequeuePodAfterBackoff, time: float
    ) -> None:
        """CrashLoopBackOff expiry: the retry enters the active queue with a
        fresh initial-attempt timestamp. Queue entry is stamped with the
        DELIVERY time — max(requeue_ts, failure-chain arrival) — which is
        the batched retry disposition's initial_attempt_ts = fail +
        max(backoff, delta_reschedule); a backoff shorter than the chain
        delay cannot beat the failure notification to the queue."""
        if data.pod_name not in self.objects_cache.pods:
            return  # removed while backing off
        self.action_queue.push(
            QueuedPodInfo(
                timestamp=time,
                attempts=1,
                initial_attempt_timestamp=time,
                pod_name=data.pod_name,
            )
        )

    def on_remove_node_from_cache(self, data: RemoveNodeFromCache, time: float) -> None:
        del self.objects_cache.nodes[data.node_name]
        n_rescheduled = self.reschedule_unfinished_pods(data.node_name, time)
        if data.crashed:
            self.metrics_collector.accumulated_metrics.pod_interruptions += (
                n_rescheduled
            )

    def on_remove_pod_from_cache(self, data: RemovePodFromCache, time: float) -> None:
        """Tolerant of finish-before-remove races
        (reference: src/core/scheduler/scheduler.rs:445-473)."""
        pod = self.objects_cache.pods.pop(data.pod_name, None)
        if pod is None:
            return  # already finished
        # Deviation from the reference (which leaks the entry and would panic in
        # move_to_active_queue_if): a removed pod must leave the unschedulable
        # queue too, else later queue scans dereference a pod no longer cached.
        self.unschedulable_pods.remove_pod(data.pod_name)
        assigned_node_name = pod.status.assigned_node
        if assigned_node_name:
            # Node may itself have been removed from cache earlier; only clean
            # up when it is still alive.
            if assigned_node_name in self.objects_cache.nodes:
                self.release_node_resources(pod)
                self.assignments[assigned_node_name].discard(data.pod_name)
                if self.config.enable_unscheduled_pods_conditional_move:
                    self._move_to_active_due_to_pod_freed_resources(
                        pod.spec.resources.requests.copy()
                    )
                else:
                    self.move_all_to_active_queue()
        # Otherwise the pod is in a scheduling queue; the pop-time existence
        # check drops it.
