"""Scheduler queue types (reference: src/core/scheduler/queue.rs).

The active queue is a min-heap by (timestamp, seq) — the explicit insertion-seq
tie-break replaces Rust BinaryHeap's unspecified equal-key order with a
deterministic one. The unschedulable map iterates in (insert_timestamp,
pod_name) order, matching the reference's BTreeMap key ordering.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

# Max time (secs) a pod may stay in unschedulable_pods before being flushed to
# the active queue regardless of resource events
# (reference: src/core/scheduler/queue.rs:8).
DEFAULT_POD_MAX_IN_UNSCHEDULABLE_PODS_DURATION = 5.0 * 60.0
# Interval (secs) of the leftover-flushing cycle
# (reference: src/core/scheduler/queue.rs:11).
POD_FLUSH_INTERVAL = 30.0


@dataclass
class QueuedPodInfo:
    """reference: src/core/scheduler/queue.rs:13-27."""

    timestamp: float
    attempts: int
    initial_attempt_timestamp: float
    pod_name: str


class ActiveQueue:
    """Min-heap of QueuedPodInfo by (timestamp, insertion seq)."""

    def __init__(self) -> None:
        self._heap: List[Tuple[float, int, QueuedPodInfo]] = []
        self._seq = 0

    def push(self, info: QueuedPodInfo) -> None:
        heapq.heappush(self._heap, (info.timestamp, self._seq, info))
        self._seq += 1

    def pop(self) -> Optional[QueuedPodInfo]:
        if not self._heap:
            return None
        return heapq.heappop(self._heap)[2]

    def __len__(self) -> int:
        return len(self._heap)


@dataclass(frozen=True)
class UnschedulablePodKey:
    """Ordered by (insert_timestamp, pod_name)
    (reference: src/core/scheduler/queue.rs:50-75)."""

    pod_name: str
    insert_timestamp: float

    def sort_key(self) -> Tuple[float, str]:
        return (self.insert_timestamp, self.pod_name)


class UnschedulableQueue:
    """(insert_timestamp, pod_name)-ordered map of QueuedPodInfo."""

    def __init__(self) -> None:
        self._map: Dict[UnschedulablePodKey, QueuedPodInfo] = {}

    def insert(self, key: UnschedulablePodKey, info: QueuedPodInfo) -> None:
        self._map[key] = info

    def remove(self, key: UnschedulablePodKey) -> QueuedPodInfo:
        return self._map.pop(key)

    def sorted_items(self) -> Iterator[Tuple[UnschedulablePodKey, QueuedPodInfo]]:
        for key in sorted(self._map, key=UnschedulablePodKey.sort_key):
            yield key, self._map[key]

    def sorted_keys(self) -> List[UnschedulablePodKey]:
        return sorted(self._map, key=UnschedulablePodKey.sort_key)

    def remove_pod(self, pod_name: str) -> None:
        """Drop every entry for a pod (used when the pod is removed outright)."""
        stale = [key for key in self._map if key.pod_name == pod_name]
        for key in stale:
            del self._map[key]

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, key: UnschedulablePodKey) -> bool:
        return key in self._map
