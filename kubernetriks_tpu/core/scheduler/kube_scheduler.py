"""Default profile-based filter->score scheduling algorithm
(reference: src/core/scheduler/kube_scheduler.rs)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetriks_tpu.core.scheduler.interface import (
    PodSchedulingAlgorithm,
    ScheduleError,
    SchedulingFailure,
)
from kubernetriks_tpu.core.scheduler.plugins import (
    BALANCED,
    FIT,
    FilterPlugin,
    LEAST_ALLOCATED,
    MOST_ALLOCATED,
    PLUGIN_REGISTRY,
    ScorePlugin,
)
from kubernetriks_tpu.core.types import Node, Pod

DEFAULT_SCHEDULER_NAME = "default_scheduler"


@dataclass
class Plugin:
    name: str
    weight: Optional[float] = None


@dataclass
class Plugins:
    filter: List[Plugin] = field(default_factory=list)
    score: List[Plugin] = field(default_factory=list)


@dataclass
class KubeSchedulerProfile:
    scheduler_name: str
    plugins: Plugins


@dataclass
class KubeSchedulerConfig:
    profiles: Dict[str, KubeSchedulerProfile] = field(default_factory=dict)


def default_kube_scheduler_config() -> KubeSchedulerConfig:
    """Fit filter + LeastAllocatedResources score at weight 1.0
    (reference: src/core/scheduler/kube_scheduler.rs:44-61)."""
    return kube_scheduler_config_from_spec("default")


# Named profile specs — the shared catalogue both paths resolve: the scalar
# KubeScheduler builds its plugin refs from these, and the batched device
# pipeline (kubernetriks_tpu/batched/pipeline.py) lowers the same specs into
# compiled kernel statics. Each value is (filter names, (scorer, weight)...).
NAMED_PROFILE_SPECS: Dict[str, tuple] = {
    # The reference default (kube_scheduler.rs:44-61): spread pods by free
    # share.
    "default": ((FIT,), ((LEAST_ALLOCATED, 1.0),)),
    # Best-fit packing — the policy the RL bimodal proof discovers: the
    # tightest-fitting node wins, keeping whole nodes free for large pods.
    "best_fit": ((FIT,), ((MOST_ALLOCATED, 1.0),)),
    # Weighted filter+score combination: pack first, but trade up to ~12.5
    # score points of tightness for an even cpu/ram drain.
    "balanced_packing": ((FIT,), ((MOST_ALLOCATED, 1.0), (BALANCED, 0.25))),
}


def kube_scheduler_config_from_spec(spec) -> KubeSchedulerConfig:
    """One profile spec -> KubeSchedulerConfig, accepted forms:

    - None                      -> the reference default profile;
    - "name"                    -> NAMED_PROFILE_SPECS lookup (loud on typos);
    - {"filters": [...],
       "score": [{"name":..., "weight":...}, ...]}
                                -> an explicit profile (weight defaults 1.0);
    - KubeSchedulerConfig       -> passed through.

    This is the ONE parser both backends use (the batched pipeline compiles
    its device profile from the config this returns), so a YAML
    `scheduler_profile:` block means the same thing everywhere."""
    if spec is None:
        spec = "default"
    if isinstance(spec, KubeSchedulerConfig):
        return spec
    if isinstance(spec, str):
        named = NAMED_PROFILE_SPECS.get(spec)
        if named is None:
            raise ValueError(
                f"unknown named scheduler profile {spec!r}; available: "
                f"{sorted(NAMED_PROFILE_SPECS)}"
            )
        filters, scores = named
        spec = {
            "filters": list(filters),
            "score": [{"name": n, "weight": w} for n, w in scores],
        }
    if not isinstance(spec, dict):
        raise TypeError(
            f"scheduler profile spec must be None, a named-profile string, "
            f"a mapping, or a KubeSchedulerConfig; got {type(spec).__name__}"
        )
    # Reject unknown keys LOUDLY: a typo like `scores:` would otherwise
    # yield a silently scoreless profile — the silent-wrong-profile
    # failure mode this subsystem exists to kill.
    unknown = set(spec) - {"filters", "score"}
    if unknown:
        raise ValueError(
            f"scheduler profile spec has unknown key(s) {sorted(unknown)}; "
            "expected 'filters' (list of filter plugin names) and 'score' "
            "(list of {name, weight} scorer refs)"
        )
    # Default the filter chain to Fit only when the key is ABSENT: an
    # explicit `filters: []` is a coherent profile (score every alive
    # node, no feasibility filter) and must not be silently substituted.
    filters_spec = spec.get("filters", [FIT])
    if filters_spec is None:
        filters_spec = [FIT]
    filter_refs = [Plugin(name=str(name)) for name in filters_spec]
    score_refs = []
    for entry in spec.get("score") or []:
        if isinstance(entry, str):
            entry = {"name": entry}
        bad = set(entry) - {"name", "weight"}
        if bad:
            raise ValueError(
                f"scheduler profile score entry {entry!r} has unknown "
                f"key(s) {sorted(bad)}; expected 'name' and optional "
                "'weight'"
            )
        score_refs.append(
            Plugin(
                name=str(entry["name"]),
                weight=float(entry.get("weight", 1.0)),
            )
        )
    profile = KubeSchedulerProfile(
        scheduler_name=DEFAULT_SCHEDULER_NAME,
        plugins=Plugins(filter=filter_refs, score=score_refs),
    )
    return KubeSchedulerConfig(profiles={DEFAULT_SCHEDULER_NAME: profile})


class KubeScheduler(PodSchedulingAlgorithm):
    def __init__(self, config: Optional[KubeSchedulerConfig] = None) -> None:
        self.config = config or default_kube_scheduler_config()

    def schedule_one(self, pod: Pod, nodes: Dict[str, Node]) -> str:
        """Filter then weighted-score over name-sorted nodes; argmax keeps the
        reference's `>=` tie-break: among equal max scores the last node in
        sorted-name order wins (reference: src/core/scheduler/kube_scheduler.rs:63-152)."""
        requests = pod.spec.resources.requests
        if requests.cpu == 0 and requests.ram == 0:
            raise SchedulingFailure(ScheduleError.REQUESTED_RESOURCES_ARE_ZEROS)
        if not nodes:
            raise SchedulingFailure(ScheduleError.NO_NODES_IN_CLUSTER)

        scheduler_name = pod.metadata.labels.get("scheduler_name", DEFAULT_SCHEDULER_NAME)
        profile = self.config.profiles[scheduler_name]

        filtered_nodes = [nodes[name] for name in sorted(nodes)]
        for filter_ref in profile.plugins.filter:
            plugin = PLUGIN_REGISTRY[filter_ref.name]
            assert isinstance(plugin, FilterPlugin), (
                f"{filter_ref.name!r} plugin is not a FilterPlugin"
            )
            filtered_nodes = plugin.filter(pod, filtered_nodes)

        if not filtered_nodes:
            raise SchedulingFailure(ScheduleError.NO_SUFFICIENT_RESOURCES)

        node_scores: Dict[str, float] = {
            node.metadata.name: 0.0 for node in filtered_nodes
        }
        for scorer_ref in profile.plugins.score:
            plugin = PLUGIN_REGISTRY[scorer_ref.name]
            assert isinstance(plugin, ScorePlugin), (
                f"{scorer_ref.name!r} plugin is not a ScorePlugin"
            )
            weight = 1.0 if scorer_ref.weight is None else scorer_ref.weight
            for node in filtered_nodes:
                node_scores[node.metadata.name] += (
                    plugin.score(pod, node) * weight
                )

        assigned_node = filtered_nodes[0].metadata.name
        max_score = node_scores[assigned_node]
        for node_name in sorted(node_scores):
            if node_scores[node_name] >= max_score:
                assigned_node = node_name
                max_score = node_scores[node_name]
        return assigned_node
