"""Default profile-based filter->score scheduling algorithm
(reference: src/core/scheduler/kube_scheduler.rs)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetriks_tpu.core.scheduler.interface import (
    PodSchedulingAlgorithm,
    ScheduleError,
    SchedulingFailure,
)
from kubernetriks_tpu.core.scheduler.plugins import (
    FilterPlugin,
    PLUGIN_REGISTRY,
    ScorePlugin,
)
from kubernetriks_tpu.core.types import Node, Pod

DEFAULT_SCHEDULER_NAME = "default_scheduler"


@dataclass
class Plugin:
    name: str
    weight: Optional[float] = None


@dataclass
class Plugins:
    filter: List[Plugin] = field(default_factory=list)
    score: List[Plugin] = field(default_factory=list)


@dataclass
class KubeSchedulerProfile:
    scheduler_name: str
    plugins: Plugins


@dataclass
class KubeSchedulerConfig:
    profiles: Dict[str, KubeSchedulerProfile] = field(default_factory=dict)


def default_kube_scheduler_config() -> KubeSchedulerConfig:
    """Fit filter + LeastAllocatedResources score at weight 1.0
    (reference: src/core/scheduler/kube_scheduler.rs:44-61)."""
    profile = KubeSchedulerProfile(
        scheduler_name=DEFAULT_SCHEDULER_NAME,
        plugins=Plugins(
            filter=[Plugin(name="Fit")],
            score=[Plugin(name="LeastAllocatedResources", weight=1.0)],
        ),
    )
    return KubeSchedulerConfig(profiles={DEFAULT_SCHEDULER_NAME: profile})


class KubeScheduler(PodSchedulingAlgorithm):
    def __init__(self, config: Optional[KubeSchedulerConfig] = None) -> None:
        self.config = config or default_kube_scheduler_config()

    def schedule_one(self, pod: Pod, nodes: Dict[str, Node]) -> str:
        """Filter then weighted-score over name-sorted nodes; argmax keeps the
        reference's `>=` tie-break: among equal max scores the last node in
        sorted-name order wins (reference: src/core/scheduler/kube_scheduler.rs:63-152)."""
        requests = pod.spec.resources.requests
        if requests.cpu == 0 and requests.ram == 0:
            raise SchedulingFailure(ScheduleError.REQUESTED_RESOURCES_ARE_ZEROS)
        if not nodes:
            raise SchedulingFailure(ScheduleError.NO_NODES_IN_CLUSTER)

        scheduler_name = pod.metadata.labels.get("scheduler_name", DEFAULT_SCHEDULER_NAME)
        profile = self.config.profiles[scheduler_name]

        filtered_nodes = [nodes[name] for name in sorted(nodes)]
        for filter_ref in profile.plugins.filter:
            plugin = PLUGIN_REGISTRY[filter_ref.name]
            assert isinstance(plugin, FilterPlugin), (
                f"{filter_ref.name!r} plugin is not a FilterPlugin"
            )
            filtered_nodes = plugin.filter(pod, filtered_nodes)

        if not filtered_nodes:
            raise SchedulingFailure(ScheduleError.NO_SUFFICIENT_RESOURCES)

        node_scores: Dict[str, float] = {
            node.metadata.name: 0.0 for node in filtered_nodes
        }
        for scorer_ref in profile.plugins.score:
            plugin = PLUGIN_REGISTRY[scorer_ref.name]
            assert isinstance(plugin, ScorePlugin), (
                f"{scorer_ref.name!r} plugin is not a ScorePlugin"
            )
            for node in filtered_nodes:
                node_scores[node.metadata.name] += (
                    plugin.score(pod, node) * scorer_ref.weight
                )

        assigned_node = filtered_nodes[0].metadata.name
        max_score = node_scores[assigned_node]
        for node_name in sorted(node_scores):
            if node_scores[node_name] >= max_score:
                assigned_node = node_name
                max_score = node_scores[node_name]
        return assigned_node
