"""Models simulating the latency of the scheduling algorithm itself
(reference: src/core/scheduler/model.rs)."""

from __future__ import annotations

from typing import Dict

from kubernetriks_tpu.core.types import Node, Pod


class PodSchedulingTimeModel:
    def simulate_time(self, pod: Pod, nodes: Dict[str, Node]) -> float:
        raise NotImplementedError


class ConstantTimePerNodeModel(PodSchedulingTimeModel):
    """1 microsecond per node in the cluster
    (reference: src/core/scheduler/model.rs:11-27)."""

    def __init__(self, constant_time_per_node: float = 1e-6) -> None:
        self.constant_time_per_node = constant_time_per_node

    def simulate_time(self, pod: Pod, nodes: Dict[str, Node]) -> float:
        return self.constant_time_per_node * len(nodes)
