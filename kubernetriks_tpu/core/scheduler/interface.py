"""Scheduling-algorithm interface (reference: src/core/scheduler/interface.rs)."""

from __future__ import annotations

import enum
from typing import Dict

from kubernetriks_tpu.core.types import Node, Pod


class ScheduleError(enum.Enum):
    NO_NODES_IN_CLUSTER = "NoNodesInCluster"
    NO_SUFFICIENT_RESOURCES = "NoSufficientResources"
    REQUESTED_RESOURCES_ARE_ZEROS = "RequestedResourcesAreZeros"


class SchedulingFailure(Exception):
    """Raised by schedule_one when no node can be assigned."""

    def __init__(self, error: ScheduleError) -> None:
        super().__init__(error.value)
        self.error = error


class PodSchedulingAlgorithm:
    """Any scheduler must implement schedule_one(pod, nodes) -> node name,
    raising SchedulingFailure on error (reference:
    src/core/scheduler/interface.rs:14-23). ``nodes`` is name-keyed; algorithms
    must iterate in sorted-name order for determinism parity."""

    def schedule_one(self, pod: Pod, nodes: Dict[str, Node]) -> str:
        raise NotImplementedError
