"""Pod resource-usage models.

Mirrors the reference's resource_usage package (reference:
src/core/resource_usage/{interface,constant,pod_group,helpers}.rs): a model
maps simulation time (+ optional live pod count) to a utilization fraction.
Configs carry a nested YAML string so arbitrary models stay config-driven.
"""

from __future__ import annotations

from typing import List, Optional

import yaml

from kubernetriks_tpu.core.types import ResourceUsageModelConfig


class ResourceUsageModel:
    """reference: src/core/resource_usage/interface.rs:8-10."""

    def current_usage(self, time: float, pod_count: Optional[int] = None) -> float:
        raise NotImplementedError


class ConstantResourceUsageModel(ResourceUsageModel):
    """Always returns the configured usage
    (reference: src/core/resource_usage/constant.rs:7-38)."""

    def __init__(self, usage: float) -> None:
        self.usage = usage

    @staticmethod
    def from_str(config: str) -> "ConstantResourceUsageModel":
        parsed = yaml.safe_load(config)
        return ConstantResourceUsageModel(usage=float(parsed["usage"]))

    def current_usage(self, time: float, pod_count: Optional[int] = None) -> float:
        return self.usage


class UsageUnit:
    def __init__(self, duration: float, total_load: float) -> None:
        self.duration = duration
        self.total_load = total_load


class PodGroupResourceUsageModel(ResourceUsageModel):
    """Piecewise-constant cyclic load curve anchored at pod-group creation time
    (reference: src/core/resource_usage/pod_group.rs:16-101).

    Utilization = min(1, total_load / pod_count): the group's total load is
    spread equally over the group's live pods. Poll times must be monotonically
    non-decreasing (the cursor only steps forward); going backwards raises.
    """

    def __init__(
        self, time_from_pod_group_creation: float, usage_sequence: List[UsageUnit]
    ) -> None:
        assert usage_sequence, "usage sequence cannot be empty"
        self.last_unit_start_time = time_from_pod_group_creation
        self.last_poll_time = time_from_pod_group_creation
        self.usage_sequence = usage_sequence
        self.current_idx_in_sequence = 0

    @staticmethod
    def from_str(config: str, time_from_pod_group_creation: float) -> "PodGroupResourceUsageModel":
        parsed = yaml.safe_load(config)
        units = [UsageUnit(float(u["duration"]), float(u["total_load"])) for u in parsed]
        return PodGroupResourceUsageModel(time_from_pod_group_creation, units)

    def _step_usage_until_current_time(self, time: float) -> None:
        current = self.usage_sequence[self.current_idx_in_sequence]
        while self.last_unit_start_time + current.duration <= time:
            self.last_unit_start_time += current.duration
            self.current_idx_in_sequence = (self.current_idx_in_sequence + 1) % len(
                self.usage_sequence
            )
            current = self.usage_sequence[self.current_idx_in_sequence]

    def _current_load(self, time: float) -> float:
        self._step_usage_until_current_time(time)
        return self.usage_sequence[self.current_idx_in_sequence].total_load

    def current_usage(self, time: float, pod_count: Optional[int] = None) -> float:
        if time < self.last_poll_time:
            raise RuntimeError(
                f"Trying to get current usage of time which is behind last poll "
                f"time: {time} vs {self.last_poll_time}"
            )
        self.last_poll_time = time
        return min(1.0, self._current_load(time) / pod_count)


def default_resource_usage_config(usage: float) -> ResourceUsageModelConfig:
    """Default model for pods without one: constant usage at their full request
    (reference: src/core/resource_usage/helpers.rs:8-13)."""
    return ResourceUsageModelConfig(model_name="constant", config=f"usage: {usage}")


def resource_usage_model_from_config(
    config: ResourceUsageModelConfig, pod_group_creation_time: Optional[str] = None
) -> ResourceUsageModel:
    """reference: src/core/resource_usage/helpers.rs:15-27."""
    if config.model_name == "constant":
        return ConstantResourceUsageModel.from_str(config.config)
    if config.model_name == "pod_group":
        return PodGroupResourceUsageModel.from_str(
            config.config, float(pod_group_creation_time)
        )
    raise ValueError(f"Unsupported resource usage model: {config.model_name!r}")
