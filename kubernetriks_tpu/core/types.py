"""Kubernetes object model for the simulated control plane.

Mirrors the reference's object model (reference: src/core/common.rs:33-65,
src/core/node.rs:7-94, src/core/pod.rs:7-123) — a pared-down k8s API surface:
ObjectMeta, RuntimeResources (cpu millicores / ram bytes), Node with
capacity/allocatable/conditions, Pod with requests/limits/duration/conditions.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class RuntimeResources:
    """cpu in millicores, ram in bytes (reference: src/core/common.rs:47-51)."""

    cpu: int = 0
    ram: int = 0

    def copy(self) -> "RuntimeResources":
        return RuntimeResources(self.cpu, self.ram)

    def __add__(self, other: "RuntimeResources") -> "RuntimeResources":
        return RuntimeResources(self.cpu + other.cpu, self.ram + other.ram)

    def __sub__(self, other: "RuntimeResources") -> "RuntimeResources":
        return RuntimeResources(self.cpu - other.cpu, self.ram - other.ram)

    def fits(self, requests: "RuntimeResources") -> bool:
        return requests.cpu <= self.cpu and requests.ram <= self.ram

    def is_zero(self) -> bool:
        return self.cpu == 0 and self.ram == 0

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "RuntimeResources":
        if not d:
            return RuntimeResources()
        return RuntimeResources(cpu=int(d.get("cpu", 0)), ram=int(d.get("ram", 0)))

    def to_dict(self) -> Dict[str, Any]:
        return {"cpu": self.cpu, "ram": self.ram}


@dataclass
class ObjectMeta:
    """Partial k8s ObjectMeta (reference: src/core/common.rs:33-45)."""

    name: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = 0.0

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "ObjectMeta":
        if not d:
            return ObjectMeta()
        return ObjectMeta(
            name=d.get("name", ""),
            labels=dict(d.get("labels") or {}),
            creation_timestamp=float(d.get("creation_timestamp", 0.0)),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "labels": dict(self.labels),
            "creation_timestamp": self.creation_timestamp,
        }


class NodeConditionType(str, enum.Enum):
    """reference: src/core/node.rs:13-22."""

    NODE_CREATED = "NodeCreated"
    NODE_READY = "NodeReady"
    NODE_FAILED = "NodeFailed"
    NODE_REMOVED = "NodeRemoved"
    DISK_PRESSURE = "DiskPressure"
    MEMORY_PRESSURE = "MemoryPressure"
    PID_PRESSURE = "PIDPressure"


class PodConditionType(str, enum.Enum):
    """reference: src/core/pod.rs:25-44."""

    POD_CREATED = "PodCreated"
    POD_SCHEDULED = "PodScheduled"
    POD_INITIALIZING = "PodInitializing"
    POD_RUNNING = "PodRunning"
    POD_SUCCEEDED = "PodSucceeded"
    POD_FAILED = "PodFailed"
    POD_REMOVED = "PodRemoved"


@dataclass
class Condition:
    """Shared shape of Node/Pod conditions: status is "True"/"False"/"Unknown"."""

    status: str
    condition_type: Any  # NodeConditionType | PodConditionType
    last_transition_time: float


def _update_condition(
    conditions: List[Condition], status: str, condition_type: Any, time: float
) -> None:
    """Upsert semantics shared by Node and Pod (reference: src/core/node.rs:71-94)."""
    for cond in conditions:
        if cond.condition_type == condition_type:
            cond.status = status
            cond.last_transition_time = time
            return
    conditions.append(Condition(status, condition_type, time))


@dataclass
class NodeStatus:
    allocatable: RuntimeResources = field(default_factory=RuntimeResources)
    capacity: RuntimeResources = field(default_factory=RuntimeResources)
    conditions: List[Condition] = field(default_factory=list)


@dataclass
class Node:
    """reference: src/core/node.rs:44-51."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    status: NodeStatus = field(default_factory=NodeStatus)

    @staticmethod
    def new(name: str, cpu: int, ram: int) -> "Node":
        return Node(
            metadata=ObjectMeta(name=name),
            status=NodeStatus(
                allocatable=RuntimeResources(cpu, ram),
                capacity=RuntimeResources(cpu, ram),
            ),
        )

    def update_condition(
        self, status: str, condition_type: NodeConditionType, time: float
    ) -> None:
        _update_condition(self.status.conditions, status, condition_type, time)

    def get_condition(self, condition_type: NodeConditionType) -> Optional[Condition]:
        for cond in self.status.conditions:
            if cond.condition_type == condition_type:
                return cond
        return None

    def copy(self) -> "Node":
        node = Node(
            metadata=ObjectMeta(
                self.metadata.name,
                dict(self.metadata.labels),
                self.metadata.creation_timestamp,
            ),
            status=NodeStatus(
                allocatable=self.status.allocatable.copy(),
                capacity=self.status.capacity.copy(),
                conditions=[
                    Condition(c.status, c.condition_type, c.last_transition_time)
                    for c in self.status.conditions
                ],
            ),
        )
        return node

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Node":
        """Missing allocatable defaults to capacity — node templates in configs
        and traces specify only capacity; the reference re-establishes
        allocatable=capacity at every template consumer (e.g.
        src/trace/generic.rs:98, cluster_autoscaler.rs:111); here it is
        normalized once at parse time."""
        status = d.get("status") or {}
        capacity = RuntimeResources.from_dict(status.get("capacity"))
        allocatable_raw = status.get("allocatable")
        allocatable = (
            RuntimeResources.from_dict(allocatable_raw)
            if allocatable_raw
            else capacity.copy()
        )
        return Node(
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            status=NodeStatus(allocatable=allocatable, capacity=capacity),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "metadata": self.metadata.to_dict(),
            "status": {
                "allocatable": self.status.allocatable.to_dict(),
                "capacity": self.status.capacity.to_dict(),
            },
        }


@dataclass
class ResourceUsageModelConfig:
    """Nested YAML-in-string model config (reference: src/core/resource_usage/interface.rs:13-18)."""

    model_name: str = ""
    config: str = ""

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> Optional["ResourceUsageModelConfig"]:
        if not d:
            return None
        return ResourceUsageModelConfig(
            model_name=d.get("model_name", ""), config=d.get("config", "")
        )

    def to_dict(self) -> Dict[str, Any]:
        return {"model_name": self.model_name, "config": self.config}


@dataclass
class RuntimeResourcesUsageModelConfig:
    """reference: src/core/common.rs:54-57."""

    cpu_config: Optional[ResourceUsageModelConfig] = None
    ram_config: Optional[ResourceUsageModelConfig] = None

    @staticmethod
    def from_dict(
        d: Optional[Dict[str, Any]],
    ) -> Optional["RuntimeResourcesUsageModelConfig"]:
        if not d:
            return None
        return RuntimeResourcesUsageModelConfig(
            cpu_config=ResourceUsageModelConfig.from_dict(d.get("cpu_config")),
            ram_config=ResourceUsageModelConfig.from_dict(d.get("ram_config")),
        )


@dataclass
class Resources:
    """reference: src/core/pod.rs:8-14."""

    limits: RuntimeResources = field(default_factory=RuntimeResources)
    requests: RuntimeResources = field(default_factory=RuntimeResources)
    usage_model_config: Optional[RuntimeResourcesUsageModelConfig] = None


@dataclass
class PodSpec:
    """running_duration=None means an infinitely long-running service
    (reference: src/core/pod.rs:16-23)."""

    resources: Resources = field(default_factory=Resources)
    running_duration: Optional[float] = None


@dataclass
class PodStatus:
    start_time: float = 0.0
    conditions: List[Condition] = field(default_factory=list)
    assigned_node: str = ""


@dataclass
class Pod:
    """reference: src/core/pod.rs:62-68."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @staticmethod
    def new(name: str, cpu: int, ram: int, running_duration: Optional[float]) -> "Pod":
        return Pod(
            metadata=ObjectMeta(name=name),
            spec=PodSpec(
                resources=Resources(
                    limits=RuntimeResources(cpu, ram),
                    requests=RuntimeResources(cpu, ram),
                ),
                running_duration=running_duration,
            ),
        )

    def update_condition(
        self, status: str, condition_type: PodConditionType, time: float
    ) -> None:
        _update_condition(self.status.conditions, status, condition_type, time)

    def get_condition(self, condition_type: PodConditionType) -> Optional[Condition]:
        for cond in self.status.conditions:
            if cond.condition_type == condition_type:
                return cond
        return None

    def copy(self) -> "Pod":
        return Pod(
            metadata=ObjectMeta(
                self.metadata.name,
                dict(self.metadata.labels),
                self.metadata.creation_timestamp,
            ),
            spec=PodSpec(
                resources=Resources(
                    limits=self.spec.resources.limits.copy(),
                    requests=self.spec.resources.requests.copy(),
                    usage_model_config=self.spec.resources.usage_model_config,
                ),
                running_duration=self.spec.running_duration,
            ),
            status=PodStatus(
                start_time=self.status.start_time,
                conditions=[
                    Condition(c.status, c.condition_type, c.last_transition_time)
                    for c in self.status.conditions
                ],
                assigned_node=self.status.assigned_node,
            ),
        )

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Pod":
        spec = d.get("spec") or {}
        resources = spec.get("resources") or {}
        return Pod(
            metadata=ObjectMeta.from_dict(d.get("metadata")),
            spec=PodSpec(
                resources=Resources(
                    limits=RuntimeResources.from_dict(resources.get("limits")),
                    requests=RuntimeResources.from_dict(resources.get("requests")),
                    usage_model_config=RuntimeResourcesUsageModelConfig.from_dict(
                        resources.get("usage_model_config")
                    ),
                ),
                running_duration=spec.get("running_duration"),
            ),
        )


@dataclass
class ObjectsInfo:
    """Name-keyed, sorted-iteration state maps (reference: src/core/common.rs:59-65).

    Python dicts preserve insertion order, not key order; components that rely on
    BTreeMap-sorted iteration must iterate via ``sorted_nodes``/``sorted_pods``.
    """

    nodes: Dict[str, Node] = field(default_factory=dict)
    pods: Dict[str, Pod] = field(default_factory=dict)

    def sorted_nodes(self) -> List[Node]:
        return [self.nodes[k] for k in sorted(self.nodes)]

    def sorted_pods(self) -> List[Pod]:
        return [self.pods[k] for k in sorted(self.pods)]
