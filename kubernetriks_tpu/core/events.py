"""Event vocabulary for the simulated control plane.

One dataclass per event, mirroring the reference's 30 event structs
(reference: src/core/events.rs:22-244). Python's dynamic dispatch replaces the
reference's `cast!`/`cast_box!` macros: components implement `on_<snake_case>`
methods and the kernel's EventHandler base routes by payload type.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from kubernetriks_tpu.core.types import (
    Node,
    Pod,
    PodConditionType,
    RuntimeResources,
    RuntimeResourcesUsageModelConfig,
)


# --- node lifecycle ---------------------------------------------------------


@dataclass
class CreateNodeRequest:
    """client/CA -> api server (reference: src/core/events.rs:22-25).
    recovered=True marks a chaos-engine recovery — the node returning after
    a crash as fresh capacity (kubernetriks_tpu/chaos.py); it flows the
    normal create chain and only adds fault accounting."""

    node: Node
    recovered: bool = False


@dataclass
class CreateNodeResponse:
    """persistent storage -> api server (reference: src/core/events.rs:29-32)."""

    node_name: str


@dataclass
class NodeAddedToCluster:
    """api server -> persistent storage (reference: src/core/events.rs:35-39)."""

    add_time: float
    node_name: str
    recovered: bool = False  # chaos-engine recovery (fault accounting only)


@dataclass
class RemoveNodeRequest:
    """client/CA -> api server; also api server -> node component
    (reference: src/core/events.rs:45-48). crashed=True marks a
    chaos-engine node crash (kubernetriks_tpu/chaos.py): it rides this
    exact removal chain — same interruption/reschedule semantics — and
    carries its pre-sampled repair span for the downtime metric."""

    node_name: str
    crashed: bool = False
    downtime_s: float = 0.0


@dataclass
class RemoveNodeResponse:
    """persistent storage -> api server (reference: src/core/events.rs:52-55)."""

    node_name: str


@dataclass
class NodeRemovedFromCluster:
    """node component -> api server -> persistent storage
    (reference: src/core/events.rs:58-62)."""

    removal_time: float
    node_name: str
    crashed: bool = False
    downtime_s: float = 0.0


@dataclass
class RemoveNodeFromCache:
    """persistent storage -> scheduler (reference: src/core/events.rs:67-70)."""

    node_name: str
    crashed: bool = False  # the scheduler counts crash-caused reschedules


@dataclass
class AddNodeToCache:
    """persistent storage -> scheduler (reference: src/core/events.rs:122-125)."""

    node: Node


# --- pod lifecycle ----------------------------------------------------------


@dataclass
class CreatePodRequest:
    """client/HPA -> api server (reference: src/core/events.rs:75-78)."""

    pod: Pod


@dataclass
class RemovePodRequest:
    """client/HPA -> api server (reference: src/core/events.rs:85-88)."""

    pod_name: str


@dataclass
class RemovePodResponse:
    """persistent storage -> api server (reference: src/core/events.rs:92-96)."""

    assigned_node: Optional[str]
    pod_name: str


@dataclass
class PodRemovedFromNode:
    """node component -> api server -> persistent storage
    (reference: src/core/events.rs:99-106). `removed` is False when the pod had
    already finished before the removal request reached the node."""

    removed: bool
    removal_time: float
    pod_name: str


@dataclass
class RemovePodFromCache:
    """persistent storage -> scheduler (reference: src/core/events.rs:109-112)."""

    pod_name: str


@dataclass
class PodScheduleRequest:
    """persistent storage -> scheduler (reference: src/core/events.rs:115-118)."""

    pod: Pod


@dataclass
class AssignPodToNodeRequest:
    """scheduler -> api server -> persistent storage
    (reference: src/core/events.rs:129-134)."""

    assign_time: float
    pod_name: str
    node_name: str


@dataclass
class AssignPodToNodeResponse:
    """persistent storage -> api server (reference: src/core/events.rs:138-147).
    fail_after: chaos-engine pod-failure draw for THIS attempt (seconds
    after start at which the attempt fails); None = runs to completion."""

    pod_name: str
    pod_requests: RuntimeResources
    pod_group: Optional[str]
    pod_group_creation_time: Optional[str]
    node_name: str
    pod_duration: Optional[float]
    resources_usage_model_config: Optional[RuntimeResourcesUsageModelConfig]
    fail_after: Optional[float] = None


@dataclass
class PodNotScheduled:
    """scheduler -> api server -> persistent storage
    (reference: src/core/events.rs:151-155)."""

    not_scheduled_time: float
    pod_name: str


@dataclass
class BindPodToNodeRequest:
    """api server -> node component (reference: src/core/events.rs:158-167)."""

    pod_name: str
    pod_requests: RuntimeResources
    pod_group: Optional[str]
    pod_group_creation_time: Optional[str]
    node_name: str
    pod_duration: Optional[float]
    resources_usage_model_config: Optional[RuntimeResourcesUsageModelConfig]
    fail_after: Optional[float] = None  # chaos: attempt fails this long after start


@dataclass
class BindPodToNodeResponse:
    """node component -> api server (reference: src/core/events.rs:170-175)."""

    pod_name: str
    pod_duration: Optional[float]
    node_name: str


@dataclass
class PodStartedRunning:
    """node component -> api server -> persistent storage
    (reference: src/core/events.rs:179-183)."""

    pod_name: str
    start_time: float


@dataclass
class PodFinishedRunning:
    """node component (self) -> api server -> persistent storage
    (reference: src/core/events.rs:186-192). finish_result is PodSucceeded or
    PodFailed."""

    pod_name: str
    node_name: str
    finish_time: float
    finish_result: PodConditionType


@dataclass
class RequeuePodAfterBackoff:
    """scheduler -> itself (chaos engine): deliver a CrashLoopBackOff'd pod
    into the active queue at its backoff-expiry time. The active queue is
    drained whole by each cycle (timestamps are priority, not eligibility),
    so a future-timestamped entry must not be pushed early."""

    pod_name: str
    requeue_ts: float


# --- pod groups / HPA -------------------------------------------------------


@dataclass
class CreatePodGroupRequest:
    """client -> api server (reference: src/core/events.rs:196-199). pod_group is
    a kubernetriks_tpu.autoscalers.interface.PodGroup."""

    pod_group: Any


@dataclass
class RegisterPodGroup:
    """api server -> HPA (reference: src/core/events.rs:203-206). info is a
    kubernetriks_tpu.autoscalers.interface.PodGroupInfo."""

    info: Any


# --- self-tick cycles -------------------------------------------------------


@dataclass
class RunSchedulingCycle:
    """scheduler -> itself (reference: src/core/events.rs:209-210)."""


@dataclass
class RunClusterAutoscalerCycle:
    """cluster autoscaler -> itself (reference: src/core/events.rs:213-214)."""


@dataclass
class RunHorizontalPodAutoscalerCycle:
    """HPA -> itself (reference: src/core/events.rs:217-218)."""


@dataclass
class RunPodMetricsCollectionCycle:
    """metrics collector -> itself (reference: src/core/events.rs:221-222)."""


@dataclass
class RecordGaugeMetricsCycle:
    """metrics collector -> itself (reference: src/core/events.rs:225-226)."""


@dataclass
class FlushUnschedulableQueueLeftover:
    """scheduler -> itself (reference: src/core/events.rs:246-247)."""


# --- cluster autoscaler info protocol ---------------------------------------


@dataclass
class ClusterAutoscalerRequest:
    """CA -> api server -> persistent storage (reference: src/core/events.rs:230-233).
    request_type is an autoscalers.interface.AutoscaleInfoRequestType."""

    request_type: Any


@dataclass
class ClusterAutoscalerResponse:
    """persistent storage -> api server -> CA (reference: src/core/events.rs:236-240).
    scale_up / scale_down are autoscalers.interface.{ScaleUpInfo, ScaleDownInfo}."""

    scale_up: Optional[Any]
    scale_down: Optional[Any]
