"""Node component: simulates a node (kubelet) executing pods.

Mirrors the reference's NodeComponent (reference: src/core/node_component.rs):
on bind it precomputes the pod's finish as a delayed self-event, builds cpu/ram
usage models, and tracks allocatable; on node removal it cancels all pending
finish events (the one "advanced" queue op the kernel supports); pod removal
has three outcomes (running / canceled-by-node-removal / already-finished).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Set, TYPE_CHECKING

from kubernetriks_tpu.core.events import (
    BindPodToNodeRequest,
    NodeRemovedFromCluster,
    PodFinishedRunning,
    PodRemovedFromNode,
    PodStartedRunning,
    RemoveNodeRequest,
    RemovePodRequest,
)
from kubernetriks_tpu.core.resource_usage import (
    ResourceUsageModel,
    resource_usage_model_from_config,
)
from kubernetriks_tpu.core.types import (
    Node,
    PodConditionType,
    RuntimeResources,
    RuntimeResourcesUsageModelConfig,
)
from kubernetriks_tpu.sim.kernel import EventHandler, SimulationContext

if TYPE_CHECKING:
    from kubernetriks_tpu.config import SimulationConfig


@dataclass
class RunningPodInfo:
    """reference: src/core/node_component.rs:24-31."""

    event_id: Optional[int]
    pod_group: Optional[str]
    pod_requests: RuntimeResources
    cpu_usage_model: Optional[ResourceUsageModel]
    ram_usage_model: Optional[ResourceUsageModel]


@dataclass
class NodeRuntime:
    """Installed when the component is allocated from the pool
    (reference: src/core/node_component.rs:50-54)."""

    api_server: int
    node: Node
    config: "SimulationConfig"


class NodeComponent(EventHandler):
    def __init__(self, ctx: SimulationContext) -> None:
        self.ctx = ctx
        self.runtime: Optional[NodeRuntime] = None
        self.running_pods: Dict[str, RunningPodInfo] = {}
        self.canceled_pods: Set[str] = set()
        self.removed = False
        self.removal_time = 0.0

    @property
    def id(self) -> int:
        return self.ctx.id

    def node_name(self) -> str:
        return self.runtime.node.metadata.name

    def get_node(self) -> Node:
        return self.runtime.node

    def context_name(self) -> str:
        return self.ctx.name

    def allocate_pod_requests(self, requests: RuntimeResources) -> None:
        allocatable = self.runtime.node.status.allocatable
        allocatable.cpu -= requests.cpu
        allocatable.ram -= requests.ram

    def free_pod_requests(self, requests: RuntimeResources) -> None:
        allocatable = self.runtime.node.status.allocatable
        allocatable.cpu += requests.cpu
        allocatable.ram += requests.ram

    def cancel_all_running_pods(self) -> None:
        """Cancel pending PodFinishedRunning self-events, free their resources,
        and mark the pods canceled (reference: src/core/node_component.rs:95-112)."""
        for pod_name, info in self.running_pods.items():
            self.canceled_pods.add(pod_name)
            if info.event_id is not None:
                self.ctx.cancel_event(info.event_id)
            self.free_pod_requests(info.pod_requests)
        self.running_pods.clear()

    def simulate_pod_runtime(
        self,
        event_time: float,
        pod_name: str,
        pod_requests: RuntimeResources,
        pod_group: Optional[str],
        pod_group_creation_time: Optional[str],
        pod_duration: Optional[float],
        usage_config: Optional[RuntimeResourcesUsageModelConfig],
        fail_after: Optional[float] = None,
    ) -> None:
        """reference: src/core/node_component.rs:114-176. A finite-duration pod
        schedules its own finish at +duration (+ as_to_node delay so the event
        leaves for the api server at the right simulated time); long-running
        services (duration None) never self-finish. A chaos-engine failing
        attempt (fail_after set) self-finishes EARLY with POD_FAILED — same
        cancellable self-event, so node removal interrupts it identically."""
        event_id: Optional[int] = None
        if fail_after is not None:
            delay = fail_after + self.runtime.config.as_to_node_network_delay
            event_id = self.ctx.emit_self(
                PodFinishedRunning(
                    pod_name=pod_name,
                    node_name=self.runtime.node.metadata.name,
                    finish_time=event_time + fail_after,
                    finish_result=PodConditionType.POD_FAILED,
                ),
                delay,
            )
        elif pod_duration is not None:
            delay = pod_duration + self.runtime.config.as_to_node_network_delay
            event_id = self.ctx.emit_self(
                PodFinishedRunning(
                    pod_name=pod_name,
                    node_name=self.runtime.node.metadata.name,
                    finish_time=event_time + pod_duration,
                    finish_result=PodConditionType.POD_SUCCEEDED,
                ),
                delay,
            )

        cpu_usage_model = ram_usage_model = None
        if usage_config is not None:
            if usage_config.cpu_config is not None:
                cpu_usage_model = resource_usage_model_from_config(
                    usage_config.cpu_config, pod_group_creation_time
                )
            if usage_config.ram_config is not None:
                ram_usage_model = resource_usage_model_from_config(
                    usage_config.ram_config, pod_group_creation_time
                )

        self.allocate_pod_requests(pod_requests)
        self.running_pods[pod_name] = RunningPodInfo(
            event_id=event_id,
            pod_group=pod_group,
            pod_requests=pod_requests,
            cpu_usage_model=cpu_usage_model,
            ram_usage_model=ram_usage_model,
        )

    # --- event handlers -----------------------------------------------------

    def on_bind_pod_to_node_request(
        self, data: BindPodToNodeRequest, time: float
    ) -> None:
        assert not self.removed, (
            "Pod is assigned on node which is being removed, looks like a bug."
        )
        assert data.node_name == self.node_name(), (
            f"Pod is assigned to node with different node name: pod - "
            f"{data.pod_name!r}, current node - {self.node_name()!r}, assigned "
            f"node - {data.node_name!r}"
        )
        self.simulate_pod_runtime(
            time,
            data.pod_name,
            data.pod_requests,
            data.pod_group,
            data.pod_group_creation_time,
            data.pod_duration,
            data.resources_usage_model_config,
            fail_after=data.fail_after,
        )
        self.ctx.emit(
            PodStartedRunning(pod_name=data.pod_name, start_time=time),
            self.runtime.api_server,
            self.runtime.config.as_to_node_network_delay,
        )

    def on_pod_finished_running(self, data: PodFinishedRunning, time: float) -> None:
        info = self.running_pods.pop(data.pod_name)
        self.free_pod_requests(info.pod_requests)
        self.ctx.emit_now(data, self.runtime.api_server)

    def on_remove_node_request(self, data: RemoveNodeRequest, time: float) -> None:
        assert data.node_name == self.node_name(), (
            f"Trying to remove other node than self: {data.node_name!r} vs "
            f"{self.node_name()!r}"
        )
        self.cancel_all_running_pods()
        self.ctx.emit(
            NodeRemovedFromCluster(
                removal_time=time,
                node_name=data.node_name,
                crashed=data.crashed,
                downtime_s=data.downtime_s,
            ),
            self.runtime.api_server,
            self.runtime.config.as_to_node_network_delay,
        )
        self.removed = True
        self.removal_time = time

    def on_remove_pod_request(self, data: RemovePodRequest, time: float) -> None:
        """Three outcomes (reference: src/core/node_component.rs:286-336):
        still running -> cancel + removed=True; canceled by node removal ->
        removed=True at node removal time; already finished -> removed=False."""
        pod_name = data.pod_name
        delay = self.runtime.config.as_to_node_network_delay
        if pod_name in self.running_pods:
            info = self.running_pods.pop(pod_name)
            self.free_pod_requests(info.pod_requests)
            if info.event_id is not None:
                self.ctx.cancel_event(info.event_id)
            response = PodRemovedFromNode(
                removed=True, removal_time=time, pod_name=pod_name
            )
        elif pod_name in self.canceled_pods:
            response = PodRemovedFromNode(
                removed=True, removal_time=self.removal_time, pod_name=pod_name
            )
        else:
            response = PodRemovedFromNode(
                removed=False, removal_time=0.0, pod_name=pod_name
            )
        self.ctx.emit(response, self.runtime.api_server, delay)


class NodeComponentPool:
    """Pre-registered pool of node components (reference:
    src/core/node_component_pool.rs:24-77). The reference needs this because
    DSLab cannot register handlers from inside handlers; kept here for parity
    of capacity semantics — pool exhaustion is a hard error, and capacity is
    pre-sized from the trace + autoscaler maximum before the run."""

    def __init__(self, node_number: int, sim) -> None:
        self.pool = []
        for i in range(node_number):
            context_name = f"pool_node_context_{i}"
            component = NodeComponent(sim.create_context(context_name))
            sim.add_handler(context_name, component)
            self.pool.append(component)

    def __len__(self) -> int:
        return len(self.pool)

    def allocate_component(
        self, node: Node, api_server: int, config: "SimulationConfig"
    ) -> NodeComponent:
        if not self.pool:
            raise RuntimeError("No nodes to allocate in pool")
        component = self.pool.pop(0)
        component.runtime = NodeRuntime(api_server=api_server, node=node, config=config)
        return component

    def reclaim_component(self, component: NodeComponent) -> None:
        component.runtime = None
        component.removed = False
        component.removal_time = 0.0
        component.canceled_pods.clear()
        component.running_pods.clear()
        self.pool.append(component)
