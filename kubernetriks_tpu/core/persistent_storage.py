"""Persistent storage (etcd stand-in): in-memory source of truth.

Mirrors the reference's PersistentStorage (reference:
src/core/persistent_storage.rs): persists every state change before the api
server acts on it, tracks node->pod assignments, the succeeded-pods archive and
the unscheduled-pods cache (which is exactly what cluster-autoscaler scale-up
consumes), and answers autoscaler info requests.
"""

from __future__ import annotations

from typing import Dict, Set, TYPE_CHECKING

from kubernetriks_tpu.core.events import (
    AddNodeToCache,
    AssignPodToNodeRequest,
    AssignPodToNodeResponse,
    ClusterAutoscalerRequest,
    ClusterAutoscalerResponse,
    CreateNodeRequest,
    CreateNodeResponse,
    CreatePodRequest,
    NodeAddedToCluster,
    NodeRemovedFromCluster,
    PodFinishedRunning,
    PodNotScheduled,
    PodRemovedFromNode,
    PodScheduleRequest,
    PodStartedRunning,
    RemoveNodeFromCache,
    RemoveNodeRequest,
    RemoveNodeResponse,
    RemovePodFromCache,
    RemovePodRequest,
    RemovePodResponse,
)
from kubernetriks_tpu.core.resource_usage import default_resource_usage_config
from kubernetriks_tpu.core.types import (
    Node,
    NodeConditionType,
    ObjectsInfo,
    Pod,
    PodConditionType,
    RuntimeResourcesUsageModelConfig,
)
from kubernetriks_tpu.sim.kernel import EventHandler, SimulationContext

if TYPE_CHECKING:
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.metrics.collector import MetricsCollector


class PersistentStorage(EventHandler):
    def __init__(
        self,
        api_server_id: int,
        scheduler_id: int,
        ctx: SimulationContext,
        config: "SimulationConfig",
        metrics_collector: "MetricsCollector",
    ) -> None:
        self.api_server = api_server_id
        self.scheduler = scheduler_id
        self.storage_data = ObjectsInfo()
        # node name -> set of pod names assigned to it
        self.assignments: Dict[str, Set[str]] = {}
        self.succeeded_pods: Dict[str, Pod] = {}
        # Chaos engine: permanently-failed archive (restart limit exceeded)
        # and the pod fault oracle installed by the simulator.
        self.failed_pods: Dict[str, Pod] = {}
        self.fault_oracle = None
        self.unscheduled_pods_cache: Set[str] = set()
        self.ctx = ctx
        self.config = config
        self.metrics_collector = metrics_collector

    # --- direct API ---------------------------------------------------------

    def add_node(self, node: Node) -> None:
        name = node.metadata.name
        if name in self.storage_data.nodes:
            raise RuntimeError(
                f"Trying to add node {name!r} to persistent storage which already exists"
            )
        self.storage_data.nodes[name] = node
        self.assignments[name] = set()

    def add_pod(self, pod: Pod) -> None:
        name = pod.metadata.name
        if name in self.storage_data.pods:
            raise RuntimeError(
                f"Trying to add pod {name!r} to persistent storage which already exists"
            )
        self.storage_data.pods[name] = pod

    def get_node(self, node_name: str):
        return self.storage_data.nodes.get(node_name)

    def get_pod(self, pod_name: str):
        return self.storage_data.pods.get(pod_name)

    def node_count(self) -> int:
        return len(self.storage_data.nodes)

    def pod_count(self) -> int:
        return len(self.storage_data.pods)

    def scale_up_info(self):
        """Unscheduled pods snapshot, in sorted-name order
        (reference: src/core/persistent_storage.rs:137-146)."""
        from kubernetriks_tpu.autoscalers.interface import ScaleUpInfo

        return ScaleUpInfo(
            unscheduled_pods=[
                self.storage_data.pods[name].copy()
                for name in sorted(self.unscheduled_pods_cache)
            ]
        )

    def scale_down_info(self):
        """All nodes + pods on autoscaled nodes + assignments snapshot
        (reference: src/core/persistent_storage.rs:148-168)."""
        from kubernetriks_tpu.autoscalers.interface import (
            CLUSTER_AUTOSCALER_ORIGIN_LABEL,
            ScaleDownInfo,
        )

        nodes = [node.copy() for node in self.storage_data.sorted_nodes()]
        pods_on_autoscaled_nodes: Dict[str, Pod] = {}
        for node in nodes:
            if node.metadata.labels.get("origin") != CLUSTER_AUTOSCALER_ORIGIN_LABEL:
                continue
            for pod_name in self.assignments[node.metadata.name]:
                pods_on_autoscaled_nodes[pod_name] = self.storage_data.pods[
                    pod_name
                ].copy()
        return ScaleDownInfo(
            nodes=nodes,
            pods_on_autoscaled_nodes=pods_on_autoscaled_nodes,
            assignments={name: set(pods) for name, pods in self.assignments.items()},
        )

    def _clean_up_pod_info(self, pod: Pod) -> None:
        """Release the pod's node resources and drop its assignment; tolerant of
        the node having been removed first (reference:
        src/core/persistent_storage.rs:170-183)."""
        node = self.storage_data.nodes.get(pod.status.assigned_node)
        if node is not None:
            node.status.allocatable.cpu += pod.spec.resources.requests.cpu
            node.status.allocatable.ram += pod.spec.resources.requests.ram
        node_assignments = self.assignments.get(pod.status.assigned_node)
        if node_assignments is not None:
            node_assignments.discard(pod.metadata.name)

    # --- event handlers -----------------------------------------------------

    def on_create_node_request(self, data: CreateNodeRequest, time: float) -> None:
        node_name = data.node.metadata.name
        self.add_node(data.node)
        self.ctx.emit(
            CreateNodeResponse(node_name=node_name),
            self.api_server,
            self.config.as_to_ps_network_delay,
        )

    def on_node_added_to_cluster(self, data: NodeAddedToCluster, time: float) -> None:
        node = self.storage_data.nodes[data.node_name]
        node.update_condition("True", NodeConditionType.NODE_CREATED, data.add_time)
        self.ctx.emit(
            AddNodeToCache(node=node.copy()),
            self.scheduler,
            self.config.ps_to_sched_network_delay,
        )
        self.metrics_collector.accumulated_metrics.internal.processed_nodes += 1
        if data.recovered:
            self.metrics_collector.accumulated_metrics.node_recoveries += 1

    def on_create_pod_request(self, data: CreatePodRequest, time: float) -> None:
        """Creation time is the time the pod lands in storage; pods without a
        usage model get the default constant-at-request model
        (reference: src/core/persistent_storage.rs:225-248)."""
        pod = data.pod
        pod.update_condition("True", PodConditionType.POD_CREATED, time)
        if pod.spec.resources.usage_model_config is None:
            pod.spec.resources.usage_model_config = RuntimeResourcesUsageModelConfig(
                cpu_config=default_resource_usage_config(
                    float(pod.spec.resources.requests.cpu)
                ),
                ram_config=default_resource_usage_config(
                    float(pod.spec.resources.requests.ram)
                ),
            )
        self.add_pod(pod)
        self.ctx.emit(
            PodScheduleRequest(pod=pod.copy()),
            self.scheduler,
            self.config.ps_to_sched_network_delay,
        )

    def on_assign_pod_to_node_request(
        self, data: AssignPodToNodeRequest, time: float
    ) -> None:
        pod = self.storage_data.pods[data.pod_name]
        pod.update_condition("True", PodConditionType.POD_SCHEDULED, data.assign_time)
        pod.status.assigned_node = data.node_name
        self.unscheduled_pods_cache.discard(data.pod_name)

        node = self.storage_data.nodes[data.node_name]
        node.status.allocatable.cpu -= pod.spec.resources.requests.cpu
        node.status.allocatable.ram -= pod.spec.resources.requests.ram
        self.assignments[data.node_name].add(data.pod_name)

        # Chaos engine: the attempt's failure draw happens at assignment
        # commit — the same point the batched path draws on device. The draw
        # is a pure counter-PRNG function of (cluster, slot, restarts), so a
        # later-dropped bind desyncs nothing.
        fail_after = (
            self.fault_oracle.attempt(data.pod_name, pod.spec.running_duration)
            if self.fault_oracle is not None
            else None
        )
        self.ctx.emit(
            AssignPodToNodeResponse(
                pod_name=data.pod_name,
                pod_requests=pod.spec.resources.requests.copy(),
                pod_group=pod.metadata.labels.get("pod_group"),
                pod_group_creation_time=pod.metadata.labels.get(
                    "pod_group_creation_time"
                ),
                node_name=data.node_name,
                pod_duration=pod.spec.running_duration,
                resources_usage_model_config=pod.spec.resources.usage_model_config,
                fail_after=fail_after,
            ),
            self.api_server,
            self.config.as_to_ps_network_delay,
        )

    def on_pod_not_scheduled(self, data: PodNotScheduled, time: float) -> None:
        pod = self.storage_data.pods[data.pod_name]
        pod.update_condition(
            "False", PodConditionType.POD_SCHEDULED, data.not_scheduled_time
        )
        self.unscheduled_pods_cache.add(data.pod_name)

    def on_pod_started_running(self, data: PodStartedRunning, time: float) -> None:
        pod = self.storage_data.pods[data.pod_name]
        pod.update_condition("True", PodConditionType.POD_RUNNING, data.start_time)

    def on_pod_finished_running(self, data: PodFinishedRunning, time: float) -> None:
        """A remove request may have raced ahead and dropped the pod from
        storage; the notification to the scheduler goes out regardless
        (reference: src/core/persistent_storage.rs:316-351).

        Chaos-engine failures (finish_result == POD_FAILED): a pod within
        its restart limit stays IN storage — its node resources/assignment
        are released and the scheduler will requeue it after backoff — while
        a permanently-failed pod archives like a finish, minus the duration
        stats (only successful completions count)."""
        if data.pod_name in self.storage_data.pods:
            pod = self.storage_data.pods[data.pod_name]
            if data.finish_result == PodConditionType.POD_FAILED:
                pod.update_condition("True", data.finish_result, data.finish_time)
                self._clean_up_pod_info(pod)
                if self.fault_oracle.is_permanently_failed(data.pod_name):
                    del self.storage_data.pods[data.pod_name]
                    self.failed_pods[data.pod_name] = pod
                else:
                    pod.status.assigned_node = ""
            else:
                del self.storage_data.pods[data.pod_name]
                pod.update_condition("True", data.finish_result, data.finish_time)
                self._clean_up_pod_info(pod)
                self.metrics_collector.accumulated_metrics.increment_pod_duration(
                    pod.spec.running_duration
                )
                self.succeeded_pods[data.pod_name] = pod
        self.ctx.emit(data, self.scheduler, self.config.ps_to_sched_network_delay)

    def on_remove_node_request(self, data: RemoveNodeRequest, time: float) -> None:
        del self.storage_data.nodes[data.node_name]
        del self.assignments[data.node_name]
        self.ctx.emit(
            RemoveNodeResponse(node_name=data.node_name),
            self.api_server,
            self.config.as_to_ps_network_delay,
        )

    def on_node_removed_from_cluster(
        self, data: NodeRemovedFromCluster, time: float
    ) -> None:
        self.ctx.emit(
            RemoveNodeFromCache(node_name=data.node_name, crashed=data.crashed),
            self.scheduler,
            self.config.ps_to_sched_network_delay,
        )

    def on_cluster_autoscaler_request(
        self, data: ClusterAutoscalerRequest, time: float
    ) -> None:
        """reference: src/core/persistent_storage.rs:381-412. Auto mode: scale
        up when there are unscheduled pods, otherwise offer scale-down info."""
        from kubernetriks_tpu.autoscalers.interface import AutoscaleInfoRequestType

        response = ClusterAutoscalerResponse(scale_up=None, scale_down=None)
        request_type = data.request_type
        if request_type == AutoscaleInfoRequestType.AUTO:
            if not self.unscheduled_pods_cache:
                response.scale_down = self.scale_down_info()
            else:
                response.scale_up = self.scale_up_info()
        elif request_type == AutoscaleInfoRequestType.SCALE_UP_ONLY:
            response.scale_up = self.scale_up_info()
        elif request_type == AutoscaleInfoRequestType.SCALE_DOWN_ONLY:
            response.scale_down = self.scale_down_info()
        elif request_type == AutoscaleInfoRequestType.BOTH:
            response.scale_up = self.scale_up_info()
            response.scale_down = self.scale_down_info()
        self.ctx.emit(response, self.api_server, self.config.as_to_ps_network_delay)

    def on_remove_pod_request(self, data: RemovePodRequest, time: float) -> None:
        """reference: src/core/persistent_storage.rs:413-462."""
        pod_name = data.pod_name
        if pod_name not in self.storage_data.pods:
            # Already removed or finished running - nothing to do.
            self.ctx.emit(
                RemovePodResponse(assigned_node=None, pod_name=pod_name),
                self.api_server,
                self.config.as_to_ps_network_delay,
            )
            return

        pod = self.storage_data.pods.pop(pod_name)
        pod.update_condition("True", PodConditionType.POD_REMOVED, time)
        # Deviation from the reference (which leaks the name here): a removed
        # unschedulable pod must leave the cache, else the next CA scale-up
        # snapshot dereferences a pod that is gone (reference would panic at
        # persistent_storage.rs:140-143).
        self.unscheduled_pods_cache.discard(pod_name)

        assigned_node_name = pod.status.assigned_node
        assigned_node = None
        if assigned_node_name:
            # Pod is (or was) on a node: release resources, then let the api
            # server terminate it on the node component.
            self._clean_up_pod_info(pod)
            assigned_node = assigned_node_name
        else:
            # Pod is still in scheduling queues - tell the scheduler directly.
            self.ctx.emit(
                RemovePodFromCache(pod_name=pod_name),
                self.scheduler,
                self.config.ps_to_sched_network_delay,
            )
        self.ctx.emit(
            RemovePodResponse(assigned_node=assigned_node, pod_name=pod_name),
            self.api_server,
            self.config.as_to_ps_network_delay,
        )

    def on_pod_removed_from_node(self, data: PodRemovedFromNode, time: float) -> None:
        if not data.removed:
            # Pod finished running earlier than the remove request - nothing to do.
            return
        self.ctx.emit(
            RemovePodFromCache(pod_name=data.pod_name),
            self.scheduler,
            self.config.ps_to_sched_network_delay,
        )
