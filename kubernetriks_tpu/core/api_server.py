"""kube-api-server component: the central router of the control plane.

Mirrors the reference's KubeApiServer (reference: src/core/api_server.rs):
every request/response passes through it; it owns the node-component pool and
the created-nodes map, tracks pending node-creation/node-removal/pod-removal
requests to resolve same-tick races, and expands pod groups.

Known-deviation note: the reference's RemovePodRequest handler inserts the pod
name into the *node*-removal pending set (api_server.rs:342-343) — an upstream
bug flagged in SURVEY.md §5.2. Here the pod name goes into the pod-removal
pending set, which is what the AssignPodToNodeRequest race check actually
consults.
"""

from __future__ import annotations

from typing import Dict, Optional, Set, TYPE_CHECKING

from kubernetriks_tpu.core.events import (
    AssignPodToNodeRequest,
    AssignPodToNodeResponse,
    BindPodToNodeRequest,
    ClusterAutoscalerRequest,
    ClusterAutoscalerResponse,
    CreateNodeRequest,
    CreateNodeResponse,
    CreatePodGroupRequest,
    CreatePodRequest,
    NodeAddedToCluster,
    NodeRemovedFromCluster,
    PodFinishedRunning,
    PodNotScheduled,
    PodRemovedFromNode,
    PodStartedRunning,
    RegisterPodGroup,
    RemoveNodeRequest,
    RemoveNodeResponse,
    RemovePodRequest,
    RemovePodResponse,
)
from kubernetriks_tpu.core.node_component import NodeComponent, NodeComponentPool
from kubernetriks_tpu.core.types import Node
from kubernetriks_tpu.sim.kernel import EventHandler, SimulationContext

if TYPE_CHECKING:
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.metrics.collector import MetricsCollector


class KubeApiServer(EventHandler):
    def __init__(
        self,
        persistent_storage_id: int,
        ctx: SimulationContext,
        config: "SimulationConfig",
        metrics_collector: "MetricsCollector",
        cluster_autoscaler_id: Optional[int] = None,
        horizontal_pod_autoscaler_id: Optional[int] = None,
    ) -> None:
        self.persistent_storage = persistent_storage_id
        self.cluster_autoscaler = cluster_autoscaler_id
        self.horizontal_pod_autoscaler = horizontal_pod_autoscaler_id
        self.ctx = ctx
        self.config = config
        self.node_pool: Optional[NodeComponentPool] = None
        self.pending_node_creation_requests: Dict[str, Node] = {}
        self.pending_node_removal_requests: Set[str] = set()
        self.pending_pod_removal_requests: Set[str] = set()
        self.created_nodes: Dict[str, NodeComponent] = {}
        self.metrics_collector = metrics_collector
        # Chaos engine (chaos.py): crash/recovery identity threaded across
        # the storage round-trips (name -> sampled downtime), and the pod
        # fault oracle installed by the simulator when fault injection is on.
        self.crashed_nodes_in_flight: Dict[str, float] = {}
        self.recovered_nodes_pending: Set[str] = set()
        self.fault_oracle = None

    # --- direct API (used by the simulator and tests) -----------------------

    def add_node_component(self, node_component: NodeComponent) -> None:
        node_name = node_component.node_name()
        if node_name in self.created_nodes:
            raise RuntimeError(
                f"Trying to add node {node_name!r} to api server which already exists"
            )
        self.created_nodes[node_name] = node_component

    def all_created_nodes(self):
        return list(self.created_nodes.values())

    def get_node_component(self, node_name: str) -> Optional[NodeComponent]:
        return self.created_nodes.get(node_name)

    def node_count(self) -> int:
        return len(self.created_nodes)

    def set_node_pool(self, node_pool: NodeComponentPool) -> None:
        self.node_pool = node_pool

    def _handle_create_node(self, node_name: str, add_time: float) -> None:
        """Node info is persisted — allocate the simulation component
        (reference: src/core/api_server.rs:96-115)."""
        node = self.pending_node_creation_requests.pop(node_name)
        component = self.node_pool.allocate_component(node, self.ctx.id, self.config)
        self.add_node_component(component)
        recovered = node_name in self.recovered_nodes_pending
        self.recovered_nodes_pending.discard(node_name)
        self.ctx.emit(
            NodeAddedToCluster(
                add_time=add_time, node_name=node_name, recovered=recovered
            ),
            self.persistent_storage,
            self.config.as_to_ps_network_delay,
        )

    def _handle_node_removal(self, node_name: str) -> None:
        component = self.created_nodes.pop(node_name)
        self.node_pool.reclaim_component(component)

    # --- event handlers -----------------------------------------------------

    def on_create_node_request(self, data: CreateNodeRequest, time: float) -> None:
        node = data.node
        node.status.allocatable = node.status.capacity.copy()
        self.metrics_collector.gauge_metrics.current_nodes += 1
        if data.recovered:
            self.recovered_nodes_pending.add(node.metadata.name)
        self.pending_node_creation_requests[node.metadata.name] = node
        self.ctx.emit(
            CreateNodeRequest(node=node.copy()),
            self.persistent_storage,
            self.config.as_to_ps_network_delay,
        )

    def on_create_node_response(self, data: CreateNodeResponse, time: float) -> None:
        self._handle_create_node(data.node_name, time)

    def on_create_pod_request(self, data: CreatePodRequest, time: float) -> None:
        self.metrics_collector.gauge_metrics.current_pods += 1
        self.ctx.emit(data, self.persistent_storage, self.config.as_to_ps_network_delay)

    def on_assign_pod_to_node_request(
        self, data: AssignPodToNodeRequest, time: float
    ) -> None:
        """Race checks: the scheduler may assign to a node that is being removed
        or to a pod that is being removed (reference: src/core/api_server.rs:163-193).
        Dropping the request is safe — the scheduler will reschedule/forget on
        the corresponding cache-removal event."""
        if (
            data.node_name in self.pending_node_removal_requests
            or data.node_name not in self.created_nodes
        ):
            return
        if data.pod_name in self.pending_pod_removal_requests:
            return
        self.ctx.emit(data, self.persistent_storage, self.config.as_to_ps_network_delay)

    def on_assign_pod_to_node_response(
        self, data: AssignPodToNodeResponse, time: float
    ) -> None:
        node_component = self.created_nodes[data.node_name]
        self.ctx.emit(
            BindPodToNodeRequest(
                pod_name=data.pod_name,
                pod_requests=data.pod_requests,
                pod_group=data.pod_group,
                pod_group_creation_time=data.pod_group_creation_time,
                node_name=data.node_name,
                pod_duration=data.pod_duration,
                resources_usage_model_config=data.resources_usage_model_config,
                fail_after=data.fail_after,
            ),
            node_component.id,
            self.config.as_to_node_network_delay,
        )

    def on_pod_not_scheduled(self, data: PodNotScheduled, time: float) -> None:
        self.ctx.emit(data, self.persistent_storage, self.config.as_to_ps_network_delay)

    def on_pod_started_running(self, data: PodStartedRunning, time: float) -> None:
        self.ctx.emit(data, self.persistent_storage, self.config.as_to_ps_network_delay)

    def on_pod_finished_running(self, data: PodFinishedRunning, time: float) -> None:
        from kubernetriks_tpu.core.types import PodConditionType

        metrics = self.metrics_collector
        if data.finish_result == PodConditionType.POD_FAILED:
            # Chaos-engine pod failure (chaos.py): record the restart; a pod
            # within its restart limit re-enters the scheduling queue after
            # backoff (downstream: storage keeps it, the scheduler requeues),
            # one past the limit terminates as permanently failed.
            new_restarts = self.fault_oracle.record_failure(data.pod_name)
            if new_restarts <= self.fault_oracle.restart_limit:
                metrics.accumulated_metrics.pod_restarts += 1
            else:
                metrics.accumulated_metrics.pods_failed += 1
                metrics.accumulated_metrics.internal.terminated_pods += 1
                metrics.gauge_metrics.current_pods -= 1
        else:
            metrics.accumulated_metrics.internal.terminated_pods += 1
            metrics.accumulated_metrics.pods_succeeded += 1
            metrics.gauge_metrics.current_pods -= 1
        self.ctx.emit(data, self.persistent_storage, self.config.as_to_ps_network_delay)

    def on_remove_node_request(self, data: RemoveNodeRequest, time: float) -> None:
        self.pending_node_removal_requests.add(data.node_name)
        if data.crashed:
            self.crashed_nodes_in_flight[data.node_name] = data.downtime_s
        self.ctx.emit(data, self.persistent_storage, self.config.as_to_ps_network_delay)

    def on_remove_node_response(self, data: RemoveNodeResponse, time: float) -> None:
        node_component = self.created_nodes[data.node_name]
        downtime = self.crashed_nodes_in_flight.pop(data.node_name, None)
        self.ctx.emit(
            RemoveNodeRequest(
                node_name=data.node_name,
                crashed=downtime is not None,
                downtime_s=downtime or 0.0,
            ),
            node_component.id,
            self.config.as_to_node_network_delay,
        )

    def on_node_removed_from_cluster(
        self, data: NodeRemovedFromCluster, time: float
    ) -> None:
        self.metrics_collector.gauge_metrics.current_nodes -= 1
        if data.crashed:
            # Crash accounting lands when the node component actually went
            # down (the batched path folds it at the same effect time).
            am = self.metrics_collector.accumulated_metrics
            am.node_crashes += 1
            am.node_downtime_s += data.downtime_s
        self._handle_node_removal(data.node_name)
        self.pending_node_removal_requests.discard(data.node_name)
        self.ctx.emit(data, self.persistent_storage, self.config.as_to_ps_network_delay)

    def on_cluster_autoscaler_request(
        self, data: ClusterAutoscalerRequest, time: float
    ) -> None:
        self.ctx.emit(data, self.persistent_storage, self.config.as_to_ps_network_delay)

    def on_cluster_autoscaler_response(
        self, data: ClusterAutoscalerResponse, time: float
    ) -> None:
        self.ctx.emit(data, self.cluster_autoscaler, self.config.as_to_ca_network_delay)

    def on_remove_pod_request(self, data: RemovePodRequest, time: float) -> None:
        self.pending_pod_removal_requests.add(data.pod_name)
        self.ctx.emit(data, self.persistent_storage, self.config.as_to_ps_network_delay)

    def on_remove_pod_response(self, data: RemovePodResponse, time: float) -> None:
        if data.assigned_node is not None:
            node_component = self.created_nodes.get(data.assigned_node)
            if node_component is None:
                # The pod's node was removed while this pod-removal was in
                # flight; the node can no longer confirm, so confirm on its
                # behalf: the self-emitted PodRemovedFromNode flows through the
                # normal handler (metrics, pending cleanup) and on to storage,
                # which tells the scheduler to drop the pod — without this the
                # scheduler would reschedule a pod storage already removed.
                # (Deviation: the reference unwraps and panics here.)
                self.ctx.emit_now(
                    PodRemovedFromNode(
                        removed=True, removal_time=time, pod_name=data.pod_name
                    ),
                    self.ctx.id,
                )
                return
            self.ctx.emit(
                RemovePodRequest(pod_name=data.pod_name),
                node_component.id,
                self.config.as_to_node_network_delay,
            )
        else:
            self.pending_pod_removal_requests.discard(data.pod_name)

    def on_pod_removed_from_node(self, data: PodRemovedFromNode, time: float) -> None:
        self.pending_pod_removal_requests.discard(data.pod_name)
        if data.removed:
            metrics = self.metrics_collector
            metrics.accumulated_metrics.internal.terminated_pods += 1
            metrics.accumulated_metrics.pods_removed += 1
            metrics.gauge_metrics.current_pods -= 1
        self.ctx.emit(data, self.persistent_storage, self.config.as_to_ps_network_delay)

    def on_create_pod_group_request(
        self, data: CreatePodGroupRequest, time: float
    ) -> None:
        """Expand the group template into initial_pod_count CreatePodRequests and
        register the group with the HPA (reference: src/core/api_server.rs:405-455)."""
        from kubernetriks_tpu.autoscalers.interface import PodGroupInfo

        pod_group = data.pod_group
        assert pod_group.pod_template.spec.running_duration is None, (
            "Pod groups with specified duration are not supported. "
            "Only long running services."
        )
        info = PodGroupInfo(creation_time=time, pod_group=pod_group)
        for idx in range(pod_group.initial_pod_count):
            pod = pod_group.pod_template.copy()
            pod_name = f"{pod_group.name}_{idx}"
            pod.metadata.name = pod_name
            pod.metadata.labels["pod_group"] = pod_group.name
            pod.metadata.labels["pod_group_creation_time"] = repr(time)
            pod.spec.resources.usage_model_config = pod_group.resources_usage_model_config
            self.ctx.emit(
                CreatePodRequest(pod=pod),
                self.persistent_storage,
                self.config.as_to_ps_network_delay,
            )
            info.created_pods.add(pod_name)
            info.total_created += 1

        self.metrics_collector.gauge_metrics.current_pods += pod_group.initial_pod_count

        if self.horizontal_pod_autoscaler is not None:
            self.ctx.emit(
                RegisterPodGroup(info=info),
                self.horizontal_pod_autoscaler,
                self.config.as_to_hpa_network_delay,
            )
