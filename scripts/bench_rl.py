"""PPO scheduler-policy benchmark (BASELINE.md tracked config 5: PPO policy
over 8192 clusters).

Phase 1: one full PPO iteration (rollout -> GAE -> clipped updates) over
8192 simulated 8-node clusters on the attached chip; reports wall-clock and
decision throughput.
Phase 2: 10 training iterations at a smaller batch on a contended workload;
reports the mean-reward trajectory to demonstrate learning.

Prints one JSON line per phase.
Usage: python scripts/bench_rl.py [n_clusters] [--skip-learning] [--attention]

--attention benches the attention policy head (rl/attention_policy.py)
instead of the MLP. Its PPO update is a much larger XLA program (self-
attention backward over the (T*C, N) batch) whose padded intermediates
exceed the tunneled dev TPU's compile/memory budget above ~2048 clusters,
so above that the update runs with gradient accumulation over <=1024-cluster
chunks (PPOConfig.update_microbatch: one chunk-sized backward in a lax.scan,
bounded program size and HBM at any C, same gradient up to fp reduction
order).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def build(n_clusters, n_nodes=8, rate=0.5, horizon=200.0, seed=7):
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )

    config = SimulationConfig.from_yaml(
        "sim_name: rl_bench\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(n_nodes, cpu=16000, ram=32 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=rate,
        horizon=horizon,
        seed=seed,
        cpu=4000,
        ram=8 * 1024**3,
        duration_range=(20.0, 60.0),
    )
    return build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=n_clusters,
        max_pods_per_cycle=8,
    )


def build_binpack(n_clusters, seed=13):
    """4 big (16-core) + 8 small (4-core) nodes; mostly 4-core pods with
    16-core pods mixed in. A 16-core pod needs an EMPTY big node, so every
    small pod routed onto a big node can park a later big pod; aggregate
    demand fits iff small pods stay on small nodes — a policy that learns the
    routing parks (almost) nothing, a random one pays -1 per parked cycle."""
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.core.events import CreateNodeRequest, CreatePodRequest
    from kubernetriks_tpu.core.types import Node, Pod

    config = SimulationConfig.from_yaml(
        "sim_name: rl_binpack\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    GiB = 1024**3
    cluster_events = []
    for i in range(4):
        cluster_events.append(
            (0.0, CreateNodeRequest(node=Node.new(f"big_{i}", 16000, 32 * GiB)))
        )
    for i in range(8):
        cluster_events.append(
            (0.0, CreateNodeRequest(node=Node.new(f"small_{i}", 4000, 8 * GiB)))
        )

    rng = np.random.default_rng(seed)
    workload_events = []
    t = 1.0
    for i in range(120):
        big = rng.random() < 0.15
        cpu = 16000 if big else 4000
        ram = (32 if big else 8) * GiB
        workload_events.append(
            (t, CreatePodRequest(pod=Pod.new(f"pod_{i:03d}", cpu, ram, 60.0)))
        )
        t += float(rng.uniform(1.5, 3.5))
    return build_batched_from_traces(
        config, cluster_events, workload_events,
        n_clusters=n_clusters, max_pods_per_cycle=8,
    )


def main(n_clusters=8192, skip_learning=False, policy_kind="mlp") -> None:
    from kubernetriks_tpu.rl.ppo import PPOConfig, PPOTrainer

    # --- phase 1: one iteration at scale ------------------------------------
    sim = build(n_clusters)
    # Attention updates above 2048 clusters: chunk the backward (see module
    # docstring). 1024 keeps the backward's padded attention intermediates
    # ((T, Cc, heads, dim) tiles at 8-16x lane-padding expansion) well under
    # the v5e's 16G HBM; the chunk must divide the batch, so take the
    # largest divisor <= 1024.
    microbatch = 0
    if policy_kind == "attention" and n_clusters > 2048:
        microbatch = max(d for d in range(1, 1025) if n_clusters % d == 0)
    trainer = PPOTrainer(
        sim, windows_per_rollout=16,
        config=PPOConfig(epochs_per_iteration=4, update_microbatch=microbatch),
        policy_kind=policy_kind,
    )
    warm = trainer.train_iteration()  # compile
    t0 = time.perf_counter()
    result = trainer.train_iteration()
    elapsed = time.perf_counter() - t0
    print(
        json.dumps(
            {
                "metric": f"PPO iteration ({policy_kind} policy), {n_clusters}x8-node clusters, 16 windows x 8 decisions",
                "value": round(elapsed, 2),
                "unit": "s/iteration",
                "decisions_per_s": round(result["decisions"] / elapsed),
                "placements": result["placements"],
                "policy_loss": round(float(result["policy_loss"]), 4),
            }
        )
    )
    if skip_learning:
        return

    # --- phase 2: learning curve on a bin-packing-sensitive workload --------
    # Heterogeneous nodes + pod sizes: small pods fit everywhere, big pods
    # only fit big nodes. A policy that routes small pods onto small nodes
    # keeps big nodes free and avoids parking big pods (-1 reward each);
    # LeastAllocated-style spreading strands capacity. Homogeneous scenarios
    # are reward-flat (any feasible node is equivalent), so this shape is
    # what makes the learning signal non-trivial.
    sim2 = build_binpack(512)
    trainer2 = PPOTrainer(
        sim2,
        windows_per_rollout=32,
        config=PPOConfig(epochs_per_iteration=4, learning_rate=3e-3),
    )
    rewards = []
    for _ in range(10):
        out = trainer2.train_iteration()
        rewards.append(round(float(out["mean_reward"]), 4))
    print(
        json.dumps(
            {
                "metric": "PPO mean reward over 10 iterations (512 clusters, bin-packing)",
                "value": rewards[-1],
                "unit": "reward",
                "trajectory": rewards,
                "improved": bool(
                    np.mean(rewards[-3:]) > np.mean(rewards[:3])
                ),
            }
        )
    )


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 and sys.argv[1].isdigit() else 8192
    main(
        n,
        skip_learning="--skip-learning" in sys.argv,
        policy_kind="attention" if "--attention" in sys.argv else "mlp",
    )
