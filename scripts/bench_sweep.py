"""Parametrized perf sweep over (n_clusters, n_nodes, pallas on/off).

Usage: python scripts/bench_sweep.py [C:N:pallas ...]
Each spec runs the bench.py scenario scaled to that shape and prints one JSON
line per spec with decisions/s.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def run_spec(n_clusters: int, n_nodes: int, use_pallas):
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )

    config = SimulationConfig.from_yaml(
        "sim_name: bench\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(n_nodes, cpu=64000, ram=128 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=2.0,
        horizon=1000.0,
        seed=3,
        cpu=4000,
        ram=8 * 1024**3,
        duration_range=(30.0, 120.0),
    )
    sim = build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=n_clusters,
        max_pods_per_cycle=64,
        use_pallas=use_pallas,
    )

    def decisions_now() -> int:
        # Host fetch = real sync; block_until_ready alone can return early
        # on the tunneled TPU platform (see bench.py).
        import numpy as np

        return int(np.asarray(sim.state.metrics.scheduling_decisions).sum())

    sim.step_until_time(190.0)
    decisions_before = decisions_now()

    t0 = time.perf_counter()
    end = 390.0
    while end <= 1200.0:
        sim.step_until_time(end)
        end += 200.0
    decisions = decisions_now() - decisions_before
    elapsed = time.perf_counter() - t0
    print(
        json.dumps(
            {
                "C": n_clusters,
                "N": n_nodes,
                "pallas": sim.use_pallas,
                "decisions_per_s": round(decisions / elapsed),
                "elapsed_s": round(elapsed, 2),
                "decisions": int(decisions),
            }
        ),
        flush=True,
    )


def main() -> None:
    for spec in sys.argv[1:]:
        c, n, p = spec.split(":")
        pallas = {"auto": None, "on": True, "off": False}[p]
        run_spec(int(c), int(n), pallas)


if __name__ == "__main__":
    main()
