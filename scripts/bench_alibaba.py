"""Alibaba v2017 replay benchmark (BASELINE.md tracked config: Alibaba replay
~1k nodes + cluster autoscaler).

Synthesizes a reference-scale trace (1,313 machines x 64 cores, ~53k batch
tasks over one simulated day, 10% machine failures — shape per
reference experiments/{modify_traces,alibaba_demo}.ipynb), runs it through
the native C++ feeder -> compile_from_arrays -> BatchedSimulation with the
cluster autoscaler enabled, and prints one JSON line with simulated-event
throughput.

Usage: python scripts/bench_alibaba.py [n_clusters] [pod_window] [days]

days > 1 stretches the same ~53k tasks over the longer horizon — the REAL
v2017 trace's density (53,472 tasks span 8 days) — and is the sliding-pod-
window streaming demonstration.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def main(n_clusters: int = 1, pod_window: int = 0, days: int = 1) -> None:
    from kubernetriks_tpu.cli import build_batched_simulation
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.synthetic_alibaba import write_synthetic_trace_dir

    with tempfile.TemporaryDirectory() as td:
        machines, tasks, instances = write_synthetic_trace_dir(
            td, error_fraction=0.1, seed=3, horizon=days * 86400.0
        )
        config = SimulationConfig.from_yaml(
            f"""
sim_name: alibaba_replay_bench
seed: 1
scheduling_cycle_interval: 10.0
as_to_ps_network_delay: 0.050
ps_to_sched_network_delay: 0.089
sched_to_as_network_delay: 0.023
as_to_node_network_delay: 0.152
as_to_ca_network_delay: 0.67
as_to_hpa_network_delay: 0.50
trace_config:
  alibaba_cluster_trace_v2017:
    machine_events_trace_path: {machines}
    batch_task_trace_path: {tasks}
    batch_instance_trace_path: {instances}
cluster_autoscaler:
  enabled: true
  scan_interval: 10.0
  max_node_count: 200
  node_groups:
  - node_template:
      metadata:
        name: replay_ca_node
      status:
        capacity:
          cpu: 64000
          ram: 94489280512
"""
        )
        build_t0 = time.perf_counter()
        sim = build_batched_simulation(
            config, n_clusters=n_clusters, pod_window=pod_window
        )
        build_s = time.perf_counter() - build_t0

        t0 = time.perf_counter()
        sim.run_to_completion(max_time=days * 86400.0 * 20.0)
        jax.block_until_ready(sim.state.time)
        elapsed = time.perf_counter() - t0

        summary = sim.metrics_summary()
        # Simulated trace events (node lifecycle + pod creations) plus
        # scheduling decisions processed, the scalar throughput analog
        # (reference: src/simulator.rs:363-368).
        events = n_clusters * sim.n_events + summary["counters"]["scheduling_decisions"]
        print(
            json.dumps(
                {
                    "metric": (
                        f"alibaba-v2017 synthetic replay, {n_clusters}x1313 nodes "
                        f"x ~107k pods, {days} simulated day(s), cluster-autoscaler on"
                        + (f", pod_window={pod_window}" if pod_window else "")
                    ),
                    "value": round(events / elapsed),
                    "unit": "events/s",
                    "replay_wall_clock_s": round(elapsed, 1),
                    "build_s": round(build_s, 1),
                    "pods_succeeded": summary["counters"]["pods_succeeded"],
                    "scaled_up_nodes": summary["counters"]["total_scaled_up_nodes"],
                }
            )
        )


if __name__ == "__main__":
    main(
        int(sys.argv[1]) if len(sys.argv) > 1 else 1,
        int(sys.argv[2]) if len(sys.argv) > 2 else 0,
        int(sys.argv[3]) if len(sys.argv) > 3 else 1,
    )
