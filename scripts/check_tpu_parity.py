"""Real-hardware Pallas parity check (not in the test suite, which pins a
virtual CPU mesh): run the same simulation through the lax.scan path and the
Mosaic-compiled Pallas kernel ON THE ATTACHED TPU and compare final state
pytrees — all simulation state exactly, metric estimator accumulators to an
ulp (XLA tiles their folds per program).

Usage: python scripts/check_tpu_parity.py
Exits nonzero on any mismatch.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def main() -> int:
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )

    if jax.default_backend() != "tpu":
        print(f"SKIP: default backend is {jax.default_backend()!r}, not tpu")
        return 0

    config = SimulationConfig.from_yaml(
        "sim_name: tpu_parity\nseed: 9\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(96, cpu=16000, ram=32 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=3.0, horizon=400.0, seed=11, cpu=3000,
        ram=6 * 1024**3, duration_range=(15.0, 90.0),
    )

    def build(pallas, select=None):
        sim = build_batched_from_traces(
            config,
            cluster.convert_to_simulator_events(),
            workload.convert_to_simulator_events(),
            n_clusters=256,
            max_pods_per_cycle=32,
            use_pallas=pallas,
        )
        if select is not None:
            sim.use_pallas_select = select
        return sim

    # All three cycle formulations: lax.scan oracle, the fused
    # selection+cycle kernel (the dense-shape default), and the
    # sort+candidate kernel (the small-C default).
    scan_sim = build(False)
    select_sim = build(True)
    cand_sim = build(True, select=False)
    assert select_sim.use_pallas_select and not cand_sim.use_pallas_select
    for sim in (scan_sim, select_sim, cand_sim):
        sim.step_until_time(600.0)
        jax.block_until_ready(sim.state.time)

    from kubernetriks_tpu.batched.state import compare_states

    decisions = scan_sim.metrics_summary()["counters"]["scheduling_decisions"]
    failed = False
    for label, sim in (("selection", select_sim), ("candidate", cand_sim)):
        bad = compare_states(scan_sim.state, sim.state)
        for key in bad:
            print(f"MISMATCH ({label} kernel) at {key}")
        if bad:
            print(
                f"FAIL: {label} kernel: {len(bad)} mismatching leaves over "
                f"{decisions} decisions"
            )
            failed = True
    if failed:
        return 1
    print(
        f"OK: Mosaic selection+candidate kernels == scan path over "
        f"{decisions} decisions (state exact, metrics within ulp)"
    )

    # The CA autoscaler kernels (ops/autoscale_kernel.py): a composed
    # HPA+CA churn scenario with the kernels compiled by Mosaic ON THE CHIP
    # must equal the XLA while_loop walks bit-for-bit. The sliding pod
    # window (device-resident slide path) rides along.
    from kubernetriks_tpu.trace.generic import GenericWorkloadTrace

    auto_config = SimulationConfig.from_yaml(
        """
sim_name: tpu_parity_auto
seed: 9
scheduling_cycle_interval: 10.0
horizontal_pod_autoscaler:
  enabled: true
cluster_autoscaler:
  enabled: true
  scan_interval: 10.0
  max_node_count: 24
  node_groups:
  - node_template:
      metadata: {name: ca_node}
      status: {capacity: {cpu: 16000, ram: 34359738368}}
"""
    )
    group = GenericWorkloadTrace.from_yaml(
        """
events:
- timestamp: 19.5
  event_type:
    !CreatePodGroup
      pod_group:
        name: grp
        initial_pod_count: 2
        max_pod_count: 16
        pod_template:
          metadata: {name: grp}
          spec:
            resources:
              requests: {cpu: 3000, ram: 6442450944}
              limits: {cpu: 3000, ram: 6442450944}
        target_resources_usage: {cpu_utilization: 0.5}
        resources_usage_model_config:
          cpu_config:
            model_name: pod_group
            config: |
              - duration: 120.0
                total_load: 1.0
              - duration: 120.0
                total_load: 7.0
              - duration: 160.0
                total_load: 0.5
"""
    ).convert_to_simulator_events()
    churn = PoissonWorkloadTrace(
        rate_per_second=1.0, horizon=400.0, seed=13, cpu=4000,
        ram=8 * 1024**3, duration_range=(20.0, 90.0), name_prefix="plain",
    ).convert_to_simulator_events()
    auto_workload = sorted(churn + group, key=lambda e: e[0])
    auto_cluster = UniformClusterTrace(
        8, cpu=16000, ram=32 * 1024**3
    ).convert_to_simulator_events()

    def build_auto(pallas):
        return build_batched_from_traces(
            auto_config,
            auto_cluster,
            auto_workload,
            n_clusters=256,
            max_pods_per_cycle=16,
            pod_window=256,
            use_pallas=pallas,
        )

    xla_sim = build_auto(False)
    ker_sim = build_auto(True)
    for sim in (xla_sim, ker_sim):
        sim.step_until_time(600.0)
        jax.block_until_ready(sim.state.time)
    bad = compare_states(xla_sim.state, ker_sim.state)
    for key in bad:
        print(f"MISMATCH (CA kernels) at {key}")
    counters = xla_sim.metrics_summary()["counters"]
    if bad:
        print(f"FAIL: CA kernels: {len(bad)} mismatching leaves")
        return 1
    assert counters["total_scaled_up_nodes"] > 0, "CA never scaled up"
    assert counters["total_scaled_down_nodes"] > 0, "CA never scaled down"
    assert xla_sim._pod_base > 0, "pod window never slid"
    print(
        f"OK: Mosaic CA scale-up/scale-down kernels == XLA walks "
        f"({counters['total_scaled_up_nodes']} node scale-ups, "
        f"{counters['total_scaled_down_nodes']} scale-downs, "
        f"sliding window active)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
