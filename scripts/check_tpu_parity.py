"""Real-hardware Pallas parity check (not in the test suite, which pins a
virtual CPU mesh): run the same simulation through the lax.scan path and the
Mosaic-compiled Pallas kernel ON THE ATTACHED TPU and compare final state
pytrees — all simulation state exactly, metric estimator accumulators to an
ulp (XLA tiles their folds per program).

Usage: python scripts/check_tpu_parity.py
Exits nonzero on any mismatch.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def main() -> int:
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )

    if jax.default_backend() != "tpu":
        print(f"SKIP: default backend is {jax.default_backend()!r}, not tpu")
        return 0

    config = SimulationConfig.from_yaml(
        "sim_name: tpu_parity\nseed: 9\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(96, cpu=16000, ram=32 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=3.0, horizon=400.0, seed=11, cpu=3000,
        ram=6 * 1024**3, duration_range=(15.0, 90.0),
    )

    def build(pallas, select=None):
        sim = build_batched_from_traces(
            config,
            cluster.convert_to_simulator_events(),
            workload.convert_to_simulator_events(),
            n_clusters=256,
            max_pods_per_cycle=32,
            use_pallas=pallas,
        )
        if select is not None:
            sim.use_pallas_select = select
        return sim

    # All three cycle formulations: lax.scan oracle, the fused
    # selection+cycle kernel (the dense-shape default), and the
    # sort+candidate kernel (the small-C default).
    scan_sim = build(False)
    select_sim = build(True)
    cand_sim = build(True, select=False)
    assert select_sim.use_pallas_select and not cand_sim.use_pallas_select
    for sim in (scan_sim, select_sim, cand_sim):
        sim.step_until_time(600.0)
        jax.block_until_ready(sim.state.time)

    from kubernetriks_tpu.batched.state import compare_states

    decisions = scan_sim.metrics_summary()["counters"]["scheduling_decisions"]
    failed = False
    for label, sim in (("selection", select_sim), ("candidate", cand_sim)):
        bad = compare_states(scan_sim.state, sim.state)
        for key in bad:
            print(f"MISMATCH ({label} kernel) at {key}")
        if bad:
            print(
                f"FAIL: {label} kernel: {len(bad)} mismatching leaves over "
                f"{decisions} decisions"
            )
            failed = True
    if failed:
        return 1
    print(
        f"OK: Mosaic selection+candidate kernels == scan path over "
        f"{decisions} decisions (state exact, metrics within ulp)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
