"""Interleaved A/B of the HPA segment-sliced pass vs the full-width pass
on the composed scenario (same process, alternating chunks — the only
trustworthy comparison through the tunnel's ±10% variance).

A: engine default (_hpa_seg = (lo, hi) group-slot slice)
B: _hpa_seg = None (hpa_pass full-width path, the pre-slice structure)

Usage: python scripts/profile_hpa_seg_ab.py [rounds]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from profile_autoscale_cost import build  # noqa: E402 (same scenario)


def main():
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    simA = build(512, True)
    print("A seg:", simA._hpa_seg, flush=True)
    simB = build(512, True)
    simB._hpa_seg = None

    for sim in (simA, simB):
        sim.step_until_time(590.0)
        _ = int(np.asarray(sim.state.metrics.scheduling_decisions).sum())

    spans = []
    end = 790.0
    for _ in range(rounds):
        spans.append(end)
        end += 200.0
    resA, resB = [], []
    for until in spans:
        for sim, res in ((simA, resA), (simB, resB)):
            t0 = time.perf_counter()
            sim.step_until_time(until)
            _ = int(np.asarray(sim.state.metrics.scheduling_decisions).sum())
            res.append((time.perf_counter() - t0) / 20 * 1e3)  # ms/window
    print("A (seg)  ms/win:", " ".join(f"{x:.2f}" for x in resA), flush=True)
    print("B (full) ms/win:", " ".join(f"{x:.2f}" for x in resB), flush=True)


if __name__ == "__main__":
    main()
