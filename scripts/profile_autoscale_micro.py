"""Micro-attribution of the autoscaler-pass window cost.

Builds the composed profile scenario, steps to steady state, captures the
live state, then times jitted hpa_pass / ca_pass (and their due vs
not-due branches) in isolation on the chip.

Usage: python scripts/profile_autoscale_micro.py [pod_window]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

from profile_autoscale_cost import build


def timeit(f, *args, n=30):
    out = f(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = f(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n * 1e3


def main():
    from kubernetriks_tpu.batched.autoscale import (
        _ca_scale_down,
        _ca_scale_up,
        ca_pass,
        hpa_pass,
    )
    from kubernetriks_tpu.batched.timerep import TPair, t_add, t_le, t_lt

    pod_window = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    sim = build(pod_window, True)
    sim.step_until_time(600.0)
    jax.block_until_ready(sim.state.time)

    state = sim.state
    auto = state.auto
    st = sim.autoscale_statics
    consts = sim.consts
    C = state.pods.phase.shape[0]
    K_up, K_sd = sim.max_ca_pods_per_cycle, sim.max_pods_per_scale_down
    print(
        f"shapes: C={C} P={state.pods.phase.shape[1]} "
        f"N={state.nodes.alive.shape[1]} S={st.ca_slots.shape[1]} "
        f"K_up={K_up} K_sd={K_sd}"
    )

    # Window indices: one where CA is due, one where it is not.
    interval = float(np.asarray(consts.scheduling_interval))
    snap = t_add(auto.ca_next, st.ca_snap, jnp.float32(interval))
    w_due = int(np.asarray(snap.win).max())
    # A window where NOTHING is due: before every next tick.
    w_before = int(np.asarray(snap.win).min()) - 2
    print(f"w_due={w_due} w_before={w_before}")

    mkW = lambda w: jnp.full((C,), w, jnp.int32)

    hpa_j = jax.jit(lambda s, a, W: hpa_pass(s, a, st, W, consts))
    pre = (
        state.pods.phase,
        state.pods.attempts,
        state.nodes.alloc_cpu,
        state.nodes.alloc_ram,
    )
    ca_j = jax.jit(
        lambda s, a, W: ca_pass(s, a, st, W, consts, K_up, K_sd, pre=pre)
    )
    ca_k = jax.jit(
        lambda s, a, W: ca_pass(
            s, a, st, W, consts, K_up, K_sd, pre=pre, use_pallas=True
        )
    )

    print(f"hpa_pass due      : {timeit(hpa_j, state, auto, mkW(w_due)):8.3f} ms")
    print(f"hpa_pass not due  : {timeit(hpa_j, state, auto, mkW(w_before)):8.3f} ms")
    print(f"ca_pass  due      : {timeit(ca_j, state, auto, mkW(w_due)):8.3f} ms")
    print(f"ca_pass  not due  : {timeit(ca_j, state, auto, mkW(w_before)):8.3f} ms")
    print(f"ca_pass kern due  : {timeit(ca_k, state, auto, mkW(w_due)):8.3f} ms")
    print(f"ca_pass kern !due : {timeit(ca_k, state, auto, mkW(w_before)):8.3f} ms")

    # Direct bodies (no cond wrapper).
    branch = jnp.ones((C,), bool)
    up_j = jax.jit(
        lambda s, a: _ca_scale_up(
            s, a, st, branch, K_up, s.pods.phase, s.pods.attempts
        )
    )
    snap_pair = TPair(
        win=jnp.full((C,), w_due, jnp.int32), off=jnp.zeros((C,), jnp.float32)
    )
    down_j = jax.jit(
        lambda s, a: _ca_scale_down(
            s, a, st, branch, K_sd,
            s.pods.phase, s.nodes.alloc_cpu, s.nodes.alloc_ram,
            snap_pair, jnp.float32(interval),
        )
    )
    print(f"_ca_scale_up body : {timeit(up_j, state, auto):8.3f} ms")
    print(f"_ca_scale_down bod: {timeit(down_j, state, auto):8.3f} ms")

    n_ca = int(np.asarray(auto.ca_count).sum())
    ph = np.asarray(state.pods.phase)
    print(f"live CA nodes total={n_ca}, unsched={(ph == 3).sum()}")


if __name__ == "__main__":
    main()
