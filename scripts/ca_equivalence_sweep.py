"""Sweep the randomized CA-equivalence scenario across seeds (default 1..60),
comparing the batched node-count trajectory (node_count_at: pending effects
resolved at the sample time) against the scalar oracle with NO shift and NO
tolerance. The r4 exact-CA record: 0/60 divergent (2026-07-31); the test
suite pins a subset (tests/test_random_ca_equivalence.py).

Usage: python scripts/ca_equivalence_sweep.py [--conditional-move] [seed ...]"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "tests"))

from test_random_ca_equivalence import (
    CA_CONFIG_SUFFIX,
    CLUSTER_TRACE,
    make_workload,
)

from kubernetriks_tpu.batched.engine import build_batched_from_traces
from kubernetriks_tpu.sim.simulator import KubernetriksSimulation
from kubernetriks_tpu.test_util import default_test_simulation_config
from kubernetriks_tpu.trace.generic import GenericClusterTrace, GenericWorkloadTrace


def run_seed(seed, conditional_move=False):
    suffix = CA_CONFIG_SUFFIX + (
        "enable_unscheduled_pods_conditional_move: true\n" if conditional_move else ""
    )
    config = default_test_simulation_config(suffix)
    workload = make_workload(seed)
    scalar = KubernetriksSimulation(config)
    scalar.initialize(
        GenericClusterTrace.from_yaml(CLUSTER_TRACE),
        GenericWorkloadTrace.from_yaml(workload),
    )
    batched = build_batched_from_traces(
        config,
        GenericClusterTrace.from_yaml(CLUSTER_TRACE).convert_to_simulator_events(),
        GenericWorkloadTrace.from_yaml(workload).convert_to_simulator_events(),
        n_clusters=1,
    )
    ts, tb = [], []
    for t in np.arange(15.0, 800.0, 10.0):
        scalar.step_until_time(float(t))
        batched.step_until_time(float(t))
        ts.append(scalar.api_server.node_count())
        tb.append(batched.node_count_at(float(t)))
    return ts, tb


if __name__ == "__main__":
    args = [a for a in sys.argv[1:]]
    conditional = "--conditional-move" in args
    args = [a for a in args if a != "--conditional-move"]
    seeds = [int(a) for a in args] if args else list(range(1, 61))
    bad = []
    for seed in seeds:
        ts, tb = run_seed(seed, conditional_move=conditional)
        diff = [(i, s, b) for i, (s, b) in enumerate(zip(ts, tb)) if s != b]
        status = "OK " if not diff else f"{len(diff):3d} div"
        print(f"seed {seed:2d}: {status}" + (f"  first@{diff[0]}" if diff else ""))
        if diff:
            bad.append(seed)
            if len(sys.argv) > 1:
                print("  scalar ", ts)
                print("  batched", tb)
    print(f"\n{len(bad)}/{len(seeds)} divergent: {bad}")
