"""Interleaved A/B of the 128-aligned pod axis at the headline shape
(1024 x 256-node clusters): aligned (P -> 2048) vs exact-width (P=2026)
builds alternate chunks in ONE process (tunnel variance discipline).

Usage: python scripts/profile_align_ab.py [rounds]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build():
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )

    config = SimulationConfig.from_yaml(
        "sim_name: bench\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(256, cpu=64000, ram=128 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=2.0, horizon=1000.0, seed=3, cpu=4000,
        ram=8 * 1024**3, duration_range=(30.0, 120.0),
    )
    return build_batched_from_traces(
        config, cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=1024, max_pods_per_cycle=64,
    )


def main():
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    os.environ["KTPU_ALIGN_PODS"] = "1"
    simA = build()
    os.environ["KTPU_ALIGN_PODS"] = "0"
    simB = build()
    print(f"A P={simA.n_pods} B P={simB.n_pods}", flush=True)

    for sim in (simA, simB):
        sim.step_until_time(190.0)
        _ = int(np.asarray(sim.state.metrics.scheduling_decisions).sum())

    resA, resB = [], []
    end = 390.0
    for _ in range(rounds):
        for sim, res in ((simA, resA), (simB, resB)):
            before = int(np.asarray(sim.state.metrics.scheduling_decisions).sum())
            t0 = time.perf_counter()
            sim.step_until_time(end)
            d = int(np.asarray(sim.state.metrics.scheduling_decisions).sum()) - before
            res.append(d / (time.perf_counter() - t0))
        end += 200.0
    print("A (aligned) Mdec/s:", " ".join(f"{x/1e6:.2f}" for x in resA), flush=True)
    print("B (exact)   Mdec/s:", " ".join(f"{x/1e6:.2f}" for x in resB), flush=True)


if __name__ == "__main__":
    main()
