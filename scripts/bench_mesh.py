"""One-command MESH benchmark: the north-star configuration shard_mapped
over an N-device mesh.

The single-chip headline (bench.py) measures one chip; the north star
(BASELINE.md / BASELINE.json) is >=10k concurrent 1000-node clusters at
>=1M decisions/s on a v5e-8. This script runs that exact shape — the
cluster batch sharded over `jax.sharding.Mesh((devices,), ("clusters",))`,
every step dispatched once for the whole mesh through the engine's
NamedSharding path (batched/engine.py) — so the README's "~35M/s projected
on a v5e-8" claim becomes a RUNNABLE number wherever a multi-chip slice
exists, rather than rhetoric extrapolated from one chip.

On this repo's CI hardware (one tunneled chip + virtual CPU meshes) it
still runs end to end: `--devices 8` under
`XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu`
exercises the full sharded dispatch path on a virtual mesh (numbers are
then CPU numbers — useful for validating scaling structure, not absolute
throughput; the suite smoke-tests exactly that path). On a real v5e-8 the
same command line with no env override produces the driver-grade number.

Usage:
  python scripts/bench_mesh.py                   # all visible devices,
                                                 # north-star per-chip share
  python scripts/bench_mesh.py --devices 8 --clusters-per-device 1250 \
      --nodes 1000                               # explicit north star
  python scripts/bench_mesh.py --smoke           # tiny shapes (suite smoke)

Prints one JSON line:
  {"metric": "pod-scheduling decisions/sec (N-device mesh, CxM-node
    clusters)", "value": ..., "unit": "decisions/s", "vs_baseline": ...,
    "platform": "tpu"|"cpu", "devices": N}
vs_baseline is against the WHOLE-SLICE north star (1M decisions/s,
BASELINE.json) — not the per-chip share — because this line measures the
whole mesh.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

BASELINE_SLICE_DECISIONS_PER_SEC = 1_000_000.0  # v5e-8 north star


def run_mesh(
    n_devices: int,
    clusters_per_device: int,
    n_nodes: int,
    horizon: float = 1000.0,
    warm_until: float = 190.0,
    chunk: float = 200.0,
) -> dict:
    import jax
    from jax.sharding import Mesh

    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )

    devices = jax.devices()[:n_devices]
    if len(devices) < n_devices:
        raise SystemExit(
            f"need {n_devices} devices, have {len(devices)} "
            f"({devices[0].platform}); on CPU set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n_devices}"
        )
    mesh = Mesh(np.array(devices), ("clusters",))
    n_clusters = clusters_per_device * n_devices

    # Same scenario as bench.py run_shape (Poisson arrivals, kube
    # filter/score), so per-chip and mesh lines are comparable.
    config = SimulationConfig.from_yaml(
        "sim_name: bench_mesh\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(n_nodes, cpu=64000, ram=128 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=2.0,
        horizon=horizon,
        seed=3,
        cpu=4000,
        ram=8 * 1024**3,
        duration_range=(30.0, 120.0),
    )
    sim = build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=n_clusters,
        max_pods_per_cycle=64,
        mesh=mesh,
    )

    def decisions_now() -> int:
        # Device->host fetch: a REAL sync point (bench.py rationale — on the
        # tunneled TPU platform block_until_ready can return early).
        return int(np.asarray(sim.state.metrics.scheduling_decisions).sum())

    # Warm-up compiles the exact chunk shape the timed loop dispatches.
    sim.step_until_time(warm_until)
    before = decisions_now()
    t0 = time.perf_counter()
    end = warm_until + chunk
    while end <= horizon + chunk:
        sim.step_until_time(end)
        end += chunk
    decisions = decisions_now() - before
    elapsed = time.perf_counter() - t0
    rate = decisions / elapsed
    return {
        "metric": (
            f"pod-scheduling decisions/sec ({n_devices}-device mesh, "
            f"{n_clusters}x{n_nodes}-node clusters)"
        ),
        "value": round(rate),
        "unit": "decisions/s",
        "vs_baseline": round(rate / BASELINE_SLICE_DECISIONS_PER_SEC, 3),
        "platform": devices[0].platform,
        "devices": n_devices,
        "decisions": decisions,
        "elapsed_s": round(elapsed, 3),
    }


def main(argv=None) -> int:
    import jax

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--devices", type=int, default=None,
        help="mesh size (default: all visible devices)",
    )
    p.add_argument(
        "--clusters-per-device", type=int, default=1250,
        help="clusters per device (north star: 1250)",
    )
    p.add_argument(
        "--nodes", type=int, default=1000,
        help="nodes per cluster (north star: 1000)",
    )
    p.add_argument(
        "--smoke", action="store_true",
        help="tiny shapes for a fast structural check (suite smoke)",
    )
    args = p.parse_args(argv)

    n_devices = args.devices or len(jax.devices())
    if args.smoke:
        result = run_mesh(
            n_devices,
            clusters_per_device=2,
            n_nodes=8,
            horizon=200.0,
            warm_until=50.0,
            chunk=50.0,
        )
    else:
        result = run_mesh(n_devices, args.clusters_per_device, args.nodes)
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
