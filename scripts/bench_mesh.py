"""One-command MESH benchmark: the north-star configuration shard_mapped
over an N-device mesh.

The single-chip headline (bench.py) measures one chip; the north star
(BASELINE.md / BASELINE.json) is >=10k concurrent 1000-node clusters at
>=1M decisions/s on a v5e-8. This script runs that exact shape — the
cluster batch sharded over `jax.sharding.Mesh((devices,), ("clusters",))`,
every step dispatched once for the whole mesh through the engine's
NamedSharding path (batched/engine.py) — so the README's "~35M/s projected
on a v5e-8" claim becomes a RUNNABLE number wherever a multi-chip slice
exists, rather than rhetoric extrapolated from one chip.

On this repo's CI hardware (one tunneled chip + virtual CPU meshes) it
still runs end to end: `--devices 8` under
`XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu`
exercises the full sharded dispatch path on a virtual mesh (numbers are
then CPU numbers — useful for validating scaling structure, not absolute
throughput; the suite smoke-tests exactly that path). On a real v5e-8 the
same command line with no env override produces the driver-grade number.

`--composed` runs the COMPOSED + CHAOS flagship shard_mapped instead: HPA
pod groups + cluster autoscaler + sliding pod window + fault injection,
with the STREAMING trace-ingestion feeder on (bounded staging-slab ring,
`KTPU_STREAM` machinery) — the all-features-on configuration whose
"~35M/s on v5e-8" number was a projection until a mesh actually ran it.
The record documents the 2 GiB device-slide budget boundary explicitly:
what the resident whole-trace payload WOULD have uploaded per the budget
formula vs what the streaming ring actually holds (depth x segment), so
the protocol is pinned before real hardware replays it at Alibaba scale
(where the whole payload exceeds the budget and streaming is the only
path). `--out` writes the record to a JSON file (the MULTICHIP_rNN
artifact); telemetry rides along, splitting stage stalls into
feeder-not-ready vs upload-wait.

Usage:
  python scripts/bench_mesh.py                   # all visible devices,
                                                 # north-star per-chip share
  python scripts/bench_mesh.py --devices 8 --clusters-per-device 1250 \
      --nodes 1000                               # explicit north star
  python scripts/bench_mesh.py --smoke           # tiny shapes (suite smoke)
  python scripts/bench_mesh.py --composed --out MULTICHIP_r06.json
                                                 # composed+chaos flagship,
                                                 # streaming feeder on

Prints one JSON line:
  {"metric": "pod-scheduling decisions/sec (N-device mesh, CxM-node
    clusters)", "value": ..., "unit": "decisions/s", "vs_baseline": ...,
    "platform": "tpu"|"cpu", "devices": N}
vs_baseline is against the WHOLE-SLICE north star (1M decisions/s,
BASELINE.json) — not the per-chip share — because this line measures the
whole mesh.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

BASELINE_SLICE_DECISIONS_PER_SEC = 1_000_000.0  # v5e-8 north star


def run_mesh(
    n_devices: int,
    clusters_per_device: int,
    n_nodes: int,
    horizon: float = 1000.0,
    warm_until: float = 190.0,
    chunk: float = 200.0,
) -> dict:
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )

    mesh, devices = _build_mesh(n_devices)
    n_clusters = clusters_per_device * n_devices

    # Same scenario as bench.py run_shape (Poisson arrivals, kube
    # filter/score), so per-chip and mesh lines are comparable.
    config = SimulationConfig.from_yaml(
        "sim_name: bench_mesh\nseed: 1\nscheduling_cycle_interval: 10.0"
    )
    cluster = UniformClusterTrace(n_nodes, cpu=64000, ram=128 * 1024**3)
    workload = PoissonWorkloadTrace(
        rate_per_second=2.0,
        horizon=horizon,
        seed=3,
        cpu=4000,
        ram=8 * 1024**3,
        duration_range=(30.0, 120.0),
    )
    sim = build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload.convert_to_simulator_events(),
        n_clusters=n_clusters,
        max_pods_per_cycle=64,
        mesh=mesh,
    )

    def decisions_now() -> int:
        # Device->host fetch: a REAL sync point (bench.py rationale — on the
        # tunneled TPU platform block_until_ready can return early).
        return int(np.asarray(sim.state.metrics.scheduling_decisions).sum())

    # Warm-up compiles the exact chunk shape the timed loop dispatches.
    sim.step_until_time(warm_until)
    before = decisions_now()
    t0 = time.perf_counter()
    end = warm_until + chunk
    while end <= horizon + chunk:
        sim.step_until_time(end)
        end += chunk
    decisions = decisions_now() - before
    elapsed = time.perf_counter() - t0
    rate = decisions / elapsed
    return {
        "metric": (
            f"pod-scheduling decisions/sec ({n_devices}-device mesh, "
            f"{n_clusters}x{n_nodes}-node clusters)"
        ),
        "value": round(rate),
        "unit": "decisions/s",
        "vs_baseline": round(rate / BASELINE_SLICE_DECISIONS_PER_SEC, 3),
        "platform": devices[0].platform,
        "devices": n_devices,
        "decisions": decisions,
        "elapsed_s": round(elapsed, 3),
    }


def _build_mesh(n_devices: int):
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()[:n_devices]
    if len(devices) < n_devices:
        raise SystemExit(
            f"need {n_devices} devices, have {len(devices)} "
            f"({devices[0].platform}); on CPU set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n_devices}"
        )
    return Mesh(np.array(devices), ("clusters",)), devices


def run_mesh_composed(
    n_devices: int,
    clusters_per_device: int,
    n_nodes: int,
    *,
    smoke: bool = False,
    stream_depth: int = 3,
    stream_segment=None,
) -> dict:
    """The composed + chaos flagship, shard_mapped, streaming feeder ON.

    Reuses bench.run_composed (the single-chip composed line's scenario,
    warm-up, >= 5-span median protocol and in-bench machinery asserts —
    HPA scaled, CA provisioned, window slid, superspan dispatched, feeder
    staged) with the cluster batch sharded over the mesh, so the mesh
    number is the SAME protocol as the tracked single-chip number, not a
    new one. fault_injection is on: node crash/recovery chains and pod
    CrashLoopBackOff run inside the scanned superspan windows.

    The record carries the device-slide budget section: the bytes the
    resident whole-trace payload would have uploaded
    (engine._slide_payload_fits formula) vs the streaming ring's bound
    (depth x segment slabs), against the 2 GiB budget — the boundary an
    Alibaba-scale replay crosses, where streaming becomes the only path.
    """
    import bench
    from kubernetriks_tpu.batched import engine as engine_mod

    mesh, devices = _build_mesh(n_devices)
    n_clusters = clusters_per_device * n_devices
    if smoke:
        kwargs = dict(
            rate_per_second=0.375, horizon=500.0, pod_window=128,
            warm_until=290.0, t_end=490.0, step=40.0, max_group_pods=16,
            burst=(100.0, 150.0, 250.0), precompile=False,
        )
        if stream_segment is None:
            # Minimum-width slabs: force mid-run SUPERSPAN_STAGE restages
            # so the dry run exercises the staging boundary, not just the
            # feeder's happy path.
            stream_segment = 128 + 64
    else:
        kwargs = dict(pod_window=512, precompile=True)
    result = bench.run_composed(
        n_clusters,
        n_nodes,
        mesh=mesh,
        faults=True,
        superspan=True,
        stream=True,
        stream_depth=stream_depth,
        stream_segment=stream_segment,
        fast_forward=False,
        # Auto under a mesh on TPU (kernels go through shard_map); forced
        # off on CPU hosts where the Pallas path would only interpret.
        use_pallas=None if devices[0].platform == "tpu" else False,
        trace=True,
        **kwargs,
    )
    rate = result["value"]
    tel = result["telemetry"]
    # Device-slide budget boundary: what the resident path would upload
    # (the _slide_payload_fits formula) vs the streaming ring's bound.
    seg_cols = tel["feeder"]["segment_cols"]
    n_i32 = 6  # req x2, dur pair x2, create window, name ranks (HPA on)
    whole_payload = None
    # T is known post-build only; reconstruct from the feeder geometry
    # (trace_cols = T + W) — the feeder reports segment/stride, the
    # engine's budget formula is C * (T + W) * 4 * n_i32.
    trace_cols = tel["feeder"].get("trace_cols")
    if trace_cols is not None:
        whole_payload = n_clusters * trace_cols * 4 * n_i32
    stream_bound = stream_depth * n_clusters * seg_cols * 4 * n_i32
    return {
        "metric": (
            f"pod-scheduling decisions/sec ({n_devices}-device mesh, "
            f"COMPOSED+CHAOS: {n_clusters}x{n_nodes}-node clusters, "
            "HPA+CA+sliding window+faults, superspan + streaming feeder)"
        ),
        "value": round(rate),
        "unit": "decisions/s",
        "vs_baseline": round(rate / BASELINE_SLICE_DECISIONS_PER_SEC, 3),
        "platform": devices[0].platform,
        "devices": n_devices,
        "spans": result["spans"],
        "measured": True,  # a run, not a projection (cpu = dry-run scale)
        "protocol": {
            "scenario": (
                "bench.run_composed: HPA pod-group burst + CA node groups "
                "+ sliding pod window + fault_injection (node "
                "crash/recovery chains, pod CrashLoopBackOff), superspan "
                "executor + streaming feeder, cluster batch sharded over "
                "Mesh((devices,), ('clusters',))"
            ),
            "timing": (
                ">= 5 repeated timed spans, zero-decision spans dropped "
                "and disclosed, median reported with min/max spread (the "
                "r5/r7 single-chip protocol, unchanged on the mesh)"
            ),
            "hardware_command": (
                # Always the FLAGSHIP command — never --smoke, even when
                # this record came from a smoke-shaped dry run: an
                # operator following it verbatim must measure the real
                # configuration, not the toy one.
                "python scripts/bench_mesh.py --composed "
                "--out MULTICHIP_rNN.json  # on a v5e-8: no env override"
            ),
            "this_run_command": (
                "python scripts/bench_mesh.py --composed"
                + (" --smoke" if smoke else "")
                + " ; env: JAX_PLATFORMS=cpu XLA_FLAGS="
                "--xla_force_host_platform_device_count="
                f"{n_devices}"
                if devices[0].platform != "tpu"
                else "python scripts/bench_mesh.py --composed"
                + (" --smoke" if smoke else "")
            ),
            "dry_run": devices[0].platform != "tpu",
        },
        "slide_budget": {
            "budget_bytes": engine_mod._DEVICE_SLIDE_BUDGET_BYTES,
            "whole_trace_payload_bytes": whole_payload,
            "streaming_ring_bound_bytes": stream_bound,
            "stream_depth": stream_depth,
            "segment_cols": seg_cols,
            "note": (
                "streaming keeps device staging at ring_bound regardless "
                "of trace length; an Alibaba-scale replay's whole payload "
                "exceeds budget_bytes and streams through the same path "
                "this run measured"
            ),
        },
        "telemetry": tel,
    }


def main(argv=None) -> int:
    import jax

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--devices", type=int, default=None,
        help="mesh size (default: all visible devices)",
    )
    p.add_argument(
        "--clusters-per-device", type=int, default=1250,
        help="clusters per device (north star: 1250)",
    )
    p.add_argument(
        "--nodes", type=int, default=1000,
        help="nodes per cluster (north star: 1000)",
    )
    p.add_argument(
        "--smoke", action="store_true",
        help="tiny shapes for a fast structural check (suite smoke)",
    )
    p.add_argument(
        "--composed", action="store_true",
        help="composed + chaos flagship (HPA+CA+sliding window+faults) "
        "shard_mapped with the streaming feeder on, instead of the plain "
        "north-star shape",
    )
    p.add_argument(
        "--out", type=str, default=None,
        help="also write the JSON record to this path (the MULTICHIP_rNN "
        "artifact)",
    )
    p.add_argument(
        "--stream-depth", type=int, default=3,
        help="streaming feeder ring depth K (--composed only)",
    )
    p.add_argument(
        "--stream-segment", type=int, default=None,
        help="staging-slab width in payload columns (--composed only; "
        "default: minimum width on --smoke to force restages, 4x window "
        "otherwise)",
    )
    args = p.parse_args(argv)

    n_devices = args.devices or len(jax.devices())
    if args.composed:
        result = run_mesh_composed(
            n_devices,
            clusters_per_device=2 if args.smoke else args.clusters_per_device,
            n_nodes=8 if args.smoke else args.nodes,
            smoke=args.smoke,
            stream_depth=args.stream_depth,
            stream_segment=args.stream_segment,
        )
    elif args.smoke:
        result = run_mesh(
            n_devices,
            clusters_per_device=2,
            n_nodes=8,
            horizon=200.0,
            warm_until=50.0,
            chunk=50.0,
        )
    else:
        result = run_mesh(n_devices, args.clusters_per_device, args.nodes)
    print(json.dumps(result), flush=True)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(result, fh, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
