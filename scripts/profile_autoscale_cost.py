"""Attribute the composed-path window cost to the autoscaler passes.

Times steady-state window stepping on the attached chip for the composed
bench scenario (bench.py run_composed) across pod-axis sizes P, in three
variants at each P:
  - auto  : HPA + CA enabled (the composed configuration)
  - noauto: identical trace/shapes with autoscalers disabled in config
            (autoscale_statics=None -> no hpa_pass/ca_pass in the step)
The (auto - noauto) delta at each P is the autoscaler-pass cost and its
scaling with the device pod axis — the round-5 target named in
docs/DESIGN.md §2.

Usage: python scripts/profile_autoscale_cost.py [P ...]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build(pod_window, autoscalers):
    from kubernetriks_tpu.batched.engine import build_batched_from_traces
    from kubernetriks_tpu.config import SimulationConfig
    from kubernetriks_tpu.trace.generator import (
        PoissonWorkloadTrace,
        UniformClusterTrace,
    )
    from kubernetriks_tpu.trace.generic import GenericWorkloadTrace

    auto_yaml = (
        """
horizontal_pod_autoscaler:
  enabled: true
cluster_autoscaler:
  enabled: true
  scan_interval: 10.0
  max_node_count: 32
  node_groups:
  - node_template:
      metadata: {name: ca_node}
      status: {capacity: {cpu: 64000, ram: 137438953472}}
"""
        if autoscalers
        else ""
    )
    config = SimulationConfig.from_yaml(
        "sim_name: prof\nseed: 1\nscheduling_cycle_interval: 10.0\n" + auto_yaml
    )
    cluster = UniformClusterTrace(32, cpu=64000, ram=128 * 1024**3)
    plain = PoissonWorkloadTrace(
        rate_per_second=1.5,
        horizon=1000.0,
        seed=3,
        cpu=16000,
        ram=32 * 1024**3,
        duration_range=(30.0, 120.0),
        name_prefix="plain",
    )
    workload = plain.convert_to_simulator_events()
    if autoscalers:
        group = GenericWorkloadTrace.from_yaml(
            """
events:
- timestamp: 49.5
  event_type:
    !CreatePodGroup
      pod_group:
        name: grp
        initial_pod_count: 8
        max_pod_count: 64
        pod_template:
          metadata: {name: grp}
          spec:
            resources:
              requests: {cpu: 8000, ram: 17179869184}
              limits: {cpu: 8000, ram: 17179869184}
        target_resources_usage: {cpu_utilization: 0.5}
        resources_usage_model_config:
          cpu_config:
            model_name: pod_group
            config: |
              - duration: 300.0
                total_load: 4.0
              - duration: 300.0
                total_load: 24.0
              - duration: 400.0
                total_load: 2.0
"""
        ).convert_to_simulator_events()
        workload = sorted(workload + group, key=lambda e: e[0])
    return build_batched_from_traces(
        config,
        cluster.convert_to_simulator_events(),
        workload,
        n_clusters=256,
        max_pods_per_cycle=64,
        pod_window=pod_window,
        use_pallas=True,
    )


def measure(pod_window, autoscalers):
    sim = build(pod_window, autoscalers)
    sim.step_until_time(590.0)  # warm: HPA burst + slides compiled
    _ = int(np.asarray(sim.state.metrics.scheduling_decisions).sum())
    t0 = time.perf_counter()
    end = 790.0
    while end <= 1200.0:
        sim.step_until_time(end)
        end += 200.0
    decisions = int(np.asarray(sim.state.metrics.scheduling_decisions).sum())
    dt = time.perf_counter() - t0
    n_windows = (1190 - 590) / 10.0  # timed loop ends at 1190 (1390 > 1200)
    return dt / n_windows * 1e3, decisions  # ms/window


def main():
    ps = [int(a) for a in sys.argv[1:]] or [512, 1024, 2048, None]
    print(f"{'P':>8} {'auto ms/win':>12} {'noauto ms/win':>14} {'delta':>8}")
    for p in ps:
        a, _ = measure(p, True)
        b, _ = measure(p, False)
        label = p if p is not None else "resident"
        print(f"{label!s:>8} {a:12.2f} {b:14.2f} {a - b:8.2f}", flush=True)


if __name__ == "__main__":
    main()
