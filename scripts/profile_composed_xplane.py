"""Device-op anatomy of the composed-flagship window from an xplane profile.

Builds the composed bench scenario (profile_autoscale_cost.build), warms
past the compile/HPA-burst region, captures a jax.profiler trace of a
steady-state span, then aggregates the TPU device plane's op durations by
HLO op name prefix — the measured structure the optimization work starts
from (the r4 dense-window anatomy in docs/DESIGN.md was produced the same
way).

Usage: python scripts/profile_composed_xplane.py [pod_window] [span_s]
"""

import collections
import glob
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import numpy as np

from profile_autoscale_cost import build


def capture(pod_window=512, span=200.0, outdir="/tmp/ktpu_xplane"):
    # Flight recorder ON (PR 8): host spans over every dispatch phase are
    # recorded alongside the xplane capture, and — with annotate set while
    # the profiler trace is active — they ALSO land in the xplane as
    # TraceAnnotations, so the aggregation below can be correlated with
    # the engine phases directly instead of re-derived from HLO op names.
    os.environ.setdefault("KTPU_TRACE", "1")
    sim = build(pod_window, True)
    sim.step_until_time(590.0)
    _ = int(np.asarray(sim.state.metrics.scheduling_decisions).sum())
    os.makedirs(outdir, exist_ok=True)
    t0 = time.perf_counter()
    sim.tracer.annotate = True
    with jax.profiler.trace(outdir):
        sim.step_until_time(590.0 + span)
        _ = int(np.asarray(sim.state.metrics.scheduling_decisions).sum())
    sim.tracer.annotate = False
    wall = time.perf_counter() - t0
    n_windows = span / 10.0
    print(f"captured {n_windows:.0f} windows in {wall:.2f}s "
          f"({wall / n_windows * 1e3:.2f} ms/window wall)")
    rep = sim.telemetry_report()
    print("host-span anatomy of the captured region "
          "(same spans appear as TraceAnnotations in the xplane):")
    for name, s in sorted(
        rep["spans"].items(), key=lambda kv: -kv[1]["total_ms"]
    ):
        print(f"{s['total_ms']:9.2f} ms  {name} (x{s['count']})")
    print("sync budget:", rep["sync_budget"])
    return outdir, n_windows


def aggregate(outdir, n_windows):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = sorted(glob.glob(outdir + "/**/*.xplane.pb", recursive=True))
    assert paths, f"no xplane under {outdir}"
    space = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as fh:
        space.ParseFromString(fh.read())

    for plane in space.planes:
        if "TPU" not in plane.name and "/device" not in plane.name.lower():
            continue
        ev_names = dict(plane.event_metadata.items())
        per_op = collections.Counter()
        total_ps = 0
        for line in plane.lines:
            for ev in line.events:
                md = ev_names.get(ev.metadata_id)
                name = md.name if md else f"id{ev.metadata_id}"
                per_op[name] += ev.duration_ps
                total_ps += ev.duration_ps
        print(f"\n== plane: {plane.name} "
              f"(device total {total_ps / 1e12 * 1e3:.2f} ms, "
              f"{total_ps / 1e12 / n_windows * 1e3:.3f} ms/window) ==")
        # Group by cleaned op-name prefix (fusion groups, kernel names).
        groups = collections.Counter()
        for name, ps in per_op.items():
            key = name.split(".")[0].split("(")[0]
            groups[key] += ps
        for key, ps in groups.most_common(28):
            print(f"{ps / 1e12 / n_windows * 1e3:9.4f} ms/win  {key}")


def main():
    pod_window = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    span = float(sys.argv[2]) if len(sys.argv) > 2 else 200.0
    outdir, n_windows = capture(pod_window, span)
    aggregate(outdir, n_windows)


if __name__ == "__main__":
    main()
