"""Learning proof for the RL scheduler (BASELINE.json configs[4]).

Trains the MLP policy with PPO on a contended bimodal workload where
placement STRATEGY (packing vs spreading) — not capacity — decides whether
large pods ever place (see rl/evaluate.py for why LeastAllocated loses
here), then evaluates greedily on a HELD-OUT trace seed against:
  - the untrained policy (same init, greedy), and
  - the KubeScheduler batched path (Fit + LeastAllocatedResources).

Writes a JSON record (learning curve + final comparison) suitable for
docs/RL_LEARNING.json, and prints progress per iteration.

Usage: python scripts/train_rl_proof.py [--iterations 80] [--clusters 64]
       [--out docs/RL_LEARNING.json] [--policy mlp]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from kubernetriks_tpu.rl.evaluate import (
    PROOF_LARGE,
    PROOF_NODE_CPU,
    PROOF_N_NODES,
    PROOF_SMALL,
    PROOF_WINDOWS,
    eval_kube,
    eval_policy,
    make_proof_sim,
)
from kubernetriks_tpu.rl.ppo import PPOConfig, PPOTrainer

WINDOWS = PROOF_WINDOWS
TRAIN_SEED_BASE = 11_000   # train traces: seeds base, base+100, ...
HELDOUT_SEED_BASE = 91_000  # held-out eval traces (disjoint)
make_sim = make_proof_sim


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iterations", type=int, default=80)
    ap.add_argument("--clusters", type=int, default=64)
    ap.add_argument("--eval-clusters", type=int, default=64)
    ap.add_argument("--eval-every", type=int, default=10)
    ap.add_argument("--policy", choices=("mlp", "attention"), default="mlp")
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--entropy", type=float, default=0.01)
    ap.add_argument("--gamma", type=float, default=0.995)
    ap.add_argument("--lam", type=float, default=0.97)
    ap.add_argument("--shaping", type=float, default=0.2)
    ap.add_argument(
        "--size-weighted", action=argparse.BooleanOptionalAction, default=True
    )
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    train_sim = make_sim(TRAIN_SEED_BASE, args.clusters)
    windows = np.arange(WINDOWS, dtype=np.int32)
    trainer = PPOTrainer(
        train_sim,
        windows_per_rollout=WINDOWS,
        config=PPOConfig(
            learning_rate=args.lr,
            entropy_coef=args.entropy,
            gamma=args.gamma,
            gae_lambda=args.lam,
            epochs_per_iteration=4,
            reward_size_weighted=args.size_weighted,
            shaping_coef=args.shaping,
        ),
        hidden=args.hidden,
        seed=args.seed,
        policy_kind=args.policy,
    )

    # One held-out sim serves every policy eval: eval_policy rolls out from
    # sim.state functionally (only eval_kube's dispatch mutates a sim).
    heldout_sim = make_sim(HELDOUT_SEED_BASE, args.eval_clusters)

    def heldout_eval(apply=None, params=None):
        return eval_policy(
            heldout_sim, apply or trainer.policy_apply,
            trainer.params if apply is None else params, windows,
            jax.random.PRNGKey(123), greedy=True, large_cpu=PROOF_LARGE["cpu"],
        )

    # Best-fit packing baseline — shared definition with the scheduler's
    # "best_fit" device profile (rl/evaluate.py wraps the
    # MostAllocatedResources scorer from the device-plugin registry).
    from kubernetriks_tpu.rl.evaluate import bestfit_policy_apply as bestfit_apply

    kube = eval_kube(
        make_sim(HELDOUT_SEED_BASE, args.eval_clusters), windows,
        large_cpu=PROOF_LARGE["cpu"],
    )
    bestfit = heldout_eval(bestfit_apply, None)
    untrained = heldout_eval()
    print("kube   :", json.dumps(kube))
    print("bestfit:", json.dumps(bestfit))
    print("init   :", json.dumps(untrained))

    curve = []
    t0 = time.time()
    for i in range(args.iterations):
        it = trainer.train_iteration()
        it["iteration"] = i
        it["wall_s"] = round(time.time() - t0, 1)
        if (i + 1) % args.eval_every == 0 or i == args.iterations - 1:
            ev = heldout_eval()
            it["heldout"] = ev
            print(
                f"iter {i:3d} reward {it['mean_reward']:+.3f} "
                f"placements {it['placements']} | heldout "
                f"placements/c {ev['placements_per_cluster']:.1f} "
                f"parks/c {ev['park_decisions_per_cluster']:.1f} "
                f"large_placed {ev['large_placed_frac']:.2f} "
                f"qtime {ev['mean_queue_time_s']:.1f}s"
            )
        else:
            print(
                f"iter {i:3d} reward {it['mean_reward']:+.3f} "
                f"placements {it['placements']}"
            )
        curve.append(it)

    trained = heldout_eval()
    record = {
        "scenario": {
            "nodes": PROOF_N_NODES, "node_cpu": PROOF_NODE_CPU,
            "small": PROOF_SMALL, "large": PROOF_LARGE,
            "windows": WINDOWS, "train_seed_base": TRAIN_SEED_BASE,
            "heldout_seed_base": HELDOUT_SEED_BASE, "clusters": args.clusters,
            "policy": args.policy,
        },
        "kube_baseline": kube,
        "bestfit_heuristic": bestfit,
        "untrained_greedy": untrained,
        "trained_greedy": trained,
        "curve": curve,
        "train_wall_s": round(time.time() - t0, 1),
    }
    print("final  :", json.dumps(trained))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)
        print("wrote", args.out)


if __name__ == "__main__":
    main()
